"""Long-context attention: ring attention + Ulysses sequence parallelism.

The reference (2018-era MXNet) has no long-context story beyond bucketing
(SURVEY.md §5); these are the explicitly-new TPU-side capabilities the
rebuild adds as first-class citizens:

- **Ring attention** (Liu et al. 2023): the sequence axis is sharded over a
  mesh axis; K/V chunks rotate around the ring via ``lax.ppermute`` riding
  ICI while each hop's partial attention is folded in with an online
  (flash-style) softmax.  Peak memory is O(T/n) per chip and the K/V
  transfer overlaps the matmuls.
- **Ulysses / all-to-all sequence parallelism** (DeepSpeed-Ulysses): an
  ``all_to_all`` swaps sequence sharding for head sharding, full attention
  runs locally per head group, and a second all_to_all swaps back.  Cheaper
  collectives for moderate sequence lengths; requires heads % n == 0.

Both are pure jax functions usable inside ``shard_map`` (see
``ring_attention_sharded`` for the pre-wired entry point).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ring_attention", "ulysses_attention", "local_attention",
           "ring_attention_sharded", "ulysses_attention_sharded"]

_NEG_INF = -1e30


def local_attention(q, k, v, causal=False, scale=None, q_offset=0,
                    k_offset=0):
    """Plain attention on local chunks.  q: (B, Tq, H, D), k/v: (B, Tk, H, D).
    Offsets give the chunks' global positions for causal masking."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Ring attention over a sharded sequence axis.

    Call inside shard_map; q/k/v are the local (B, T/n, H, D) chunks of a
    globally (B, T, H, D) tensor sharded on `axis_name`.  Returns the local
    output chunk.  Equivalent to full softmax attention over the global
    sequence (verified against local_attention in tests)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    qpos = idx * Tl + jnp.arange(Tl)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, hop):
        o, m, l, k_cur, v_cur = carry
        src = (idx - hop) % n                        # owner of current chunk
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        if causal:
            kpos = src * Tl + jnp.arange(Tl)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_hop = jnp.max(s, axis=-1)                  # (B, H, Tq)
        m_new = jnp.maximum(m, m_hop)
        # guard fully-masked rows (exp(-inf - -inf))
        m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(jnp.where(m <= _NEG_INF / 2, _NEG_INF, m) - m_safe)
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        # rotate K/V to the next device over ICI; the compiler overlaps the
        # permute with the next hop's einsum
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((B, H, Tl), _NEG_INF, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    # mark the fresh carries as device-varying so the scan carry type is
    # consistent with the rotating k/v (shard_map vma typing)
    try:
        m0 = lax.pvary(m0, (axis_name,))
        l0 = lax.pvary(l0, (axis_name,))
    except AttributeError:
        pass
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o / denom


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """All-to-all (Ulysses) sequence parallelism.

    Local chunks (B, T/n, H, D) are re-sharded to (B, T, H/n, D) with one
    all_to_all, attended fully per local head group, and re-sharded back.
    Requires H % n == 0."""
    n = lax.psum(1, axis_name)
    B, Tl, H, D = q.shape

    def seq2head(x):
        # (B, Tl, H, D) -> (B, Tl, n, H/n, D) -> a2a over n -> (B, T, H/n, D)
        x = x.reshape(B, Tl, n, H // n, D)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=0,
                           tiled=False)
        # leading axis now n × B? all_to_all with split_axis=2, concat_axis=0
        # yields (n*B, Tl, H/n, D) — reorder to (B, n*Tl, H/n, D)
        x = x.reshape(n, B, Tl, H // n, D)
        x = x.transpose(1, 0, 2, 3, 4).reshape(B, n * Tl, H // n, D)
        return x

    def head2seq(x):
        # inverse of seq2head
        x = x.reshape(B, n, Tl, H // n, D).transpose(1, 0, 2, 3, 4)
        x = x.reshape(n * B, Tl, H // n, D)
        x = lax.all_to_all(x.reshape(n, B, Tl, H // n, D), axis_name,
                           split_axis=0, concat_axis=3, tiled=False)
        return x.reshape(B, Tl, H, D)

    qg = seq2head(q)
    kg = seq2head(k)
    vg = seq2head(v)
    o = local_attention(qg, kg, vg, causal=causal, scale=scale)
    return head2seq(o)


def _seq_sharded_spec(mesh, axis):
    return NamedSharding(mesh, PartitionSpec(None, axis, None, None))


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False):
    """jit-able global entry: q/k/v are global (B, T, H, D) arrays; the
    function shards T over `axis` and runs ring attention."""
    from jax.experimental.shard_map import shard_map
    spec = PartitionSpec(None, axis, None, None)
    fn = shard_map(partial(ring_attention, axis_name=axis, causal=causal),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, axis="sp", causal=False):
    from jax.experimental.shard_map import shard_map
    spec = PartitionSpec(None, axis, None, None)
    fn = shard_map(partial(ulysses_attention, axis_name=axis, causal=causal),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
