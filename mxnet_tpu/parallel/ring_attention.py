"""Long-context attention: ring attention + Ulysses sequence parallelism.

The reference (2018-era MXNet) has no long-context story beyond bucketing
(SURVEY.md §5); these are the explicitly-new TPU-side capabilities the
rebuild adds as first-class citizens:

- **Ring attention** (Liu et al. 2023): the sequence axis is sharded over a
  mesh axis; K/V chunks rotate around the ring via ``lax.ppermute`` riding
  ICI while each hop's partial attention is folded in with an online
  (flash-style) softmax.  Peak memory is O(T/n) per chip and the K/V
  transfer overlaps the matmuls.
- **Ulysses / all-to-all sequence parallelism** (DeepSpeed-Ulysses): an
  ``all_to_all`` swaps sequence sharding for head sharding, full attention
  runs locally per head group, and a second all_to_all swaps back.  Cheaper
  collectives for moderate sequence lengths; requires heads % n == 0.

Both are pure jax functions usable inside ``shard_map`` (see
``ring_attention_sharded`` for the pre-wired entry point).

**Now trained with, not just shipped**: the ``mxnet_tpu.transformer``
mesh tier (docs/transformer.md) wires both paths into the real
``DataParallelTrainer(mesh_plan=...)`` step — ring (or Ulysses, when
the local head count divides the sequence axis) attention runs over the
``sequence`` mesh axis inside the jitted training program, composing
with tensor parallelism over ``model`` and ZeRO-1 over ``data``; the
``tp_transformer_train_step`` and ``ulysses_attention`` budget rows in
STATIC_BUDGETS.json pin the resulting collective schedules.

The collective schedule here is a *proven* artifact: the analysis
tier's mxshard passes (``docs/analysis.md`` "Sharding propagation")
trace these functions on a declared ``sequence`` axis and verify that
every scanned ``ppermute`` is a single full ring whose modeled bytes
match the closed-form formula (K hops x chunk — DST009), that no dead
or mixed-axis reduction sneaks in (DST006/DST008), and that the
priced total (6 rotating buffers x K x chunk for forward+backward) is
pinned in ``STATIC_BUDGETS.json`` as ``ring_attention_fwd``.  Both the
ring and Ulysses paths currently lint clean with zero inline disables
(``--self-check`` sweeps them via ``lint_parallel_sources``); anyone
changing a ``perm``, hop count or accumulator rotation below will hear
about it from CI before any hardware runs it.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ring_attention", "ulysses_attention", "local_attention",
           "ring_attention_sharded", "ulysses_attention_sharded"]

_NEG_INF = -1e30


def local_attention(q, k, v, causal=False, scale=None, q_offset=0,
                    k_offset=0):
    """Plain attention on local chunks.  q: (B, Tq, H, D), k/v: (B, Tk, H, D).
    Offsets give the chunks' global positions for causal masking."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _pvary(x, axis_name):
    try:
        return lax.pvary(x, (axis_name,))
    except AttributeError:
        return x


def _to_bhtd(x):
    """(B, T, H, D) → (B*H, T, D) — the flash kernels' layout."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _from_bhtd(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _hop_cases(src, idx, causal, diag_fn, full_fn, skip_fn):
    """Causal trichotomy per ring hop: the chunk is the diagonal (aligned
    causal mask), strictly earlier (full attention) or strictly later
    (contributes nothing).  Chunks are aligned so no offset math is needed
    inside the kernels."""
    if not causal:
        return full_fn()
    return lax.cond(
        src == idx, lambda _: diag_fn(),
        lambda _: lax.cond(src < idx, lambda __: full_fn(),
                           lambda __: skip_fn(), _), operand=None)


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    from ..ops import pallas_kernels as _pk

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    qf = _to_bhtd(q)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, hop):
        o, lse, k_cur, v_cur = carry                 # o (BH,Tl,D) f32, lse f32
        src = (idx - hop) % n

        def run(c):
            out, l = _pk.flash_forward_with_lse(qf, _to_bhtd(k_cur),
                                                _to_bhtd(v_cur), c, scale)
            return out.astype(jnp.float32), l

        o_h, lse_h = _hop_cases(
            src, idx, causal,
            diag_fn=lambda: run(True),
            full_fn=lambda: run(False),
            skip_fn=lambda: (jnp.zeros_like(o),
                             jnp.full_like(lse, _NEG_INF)))
        # combine normalized chunk outputs through their logsumexps
        lse_new = jnp.logaddexp(lse, lse_h)
        safe = jnp.where(lse_new <= _NEG_INF / 2, 0.0, lse_new)
        c_old = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(lse - safe))
        c_hop = jnp.where(lse_h <= _NEG_INF / 2, 0.0, jnp.exp(lse_h - safe))
        o_new = o * c_old[..., None] + o_h * c_hop[..., None]
        # rotate K/V over ICI; the compiler overlaps the permute with the
        # next hop's kernels
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, lse_new, k_next, v_next), None

    o0 = _pvary(jnp.zeros((B * H, Tl, D), jnp.float32), axis_name)
    lse0 = _pvary(jnp.full((B * H, Tl), _NEG_INF, jnp.float32), axis_name)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return _from_bhtd(o.astype(q.dtype), B, H), lse


def _ring_bwd_impl(q, k, v, o_f, lse, do, axis_name, causal, scale):
    """Second ring pass: dq accumulates locally; (dk, dv) accumulators
    travel with their K/V chunks and are home after n hops."""
    from ..ops import pallas_kernels as _pk

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    qf = _to_bhtd(q)
    dof = _to_bhtd(do)
    delta = _pk.flash_delta(_to_bhtd(o_f), dof)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, hop):
        dq_acc, k_cur, v_cur, dk_acc, dv_acc = carry
        src = (idx - hop) % n
        kf, vf = _to_bhtd(k_cur), _to_bhtd(v_cur)

        def run(c):
            dq_h = _pk.flash_dq(qf, kf, vf, dof, lse, delta, c, scale)
            dk_h, dv_h = _pk.flash_dkv(qf, kf, vf, dof, lse, delta, c, scale)
            return (dq_h.astype(jnp.float32), dk_h.astype(jnp.float32),
                    dv_h.astype(jnp.float32))

        dq_h, dk_h, dv_h = _hop_cases(
            src, idx, causal,
            diag_fn=lambda: run(True),
            full_fn=lambda: run(False),
            skip_fn=lambda: (jnp.zeros_like(dq_acc), jnp.zeros_like(dk_acc),
                             jnp.zeros_like(dv_acc)))
        dq_acc = dq_acc + dq_h
        dk_acc = dk_acc + dk_h
        dv_acc = dv_acc + dv_h
        # the chunk gradients rotate with their chunk: after n hops each
        # (dk, dv) accumulator is back on the chunk's owner
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk_next = lax.ppermute(dk_acc, axis_name, perm)
        dv_next = lax.ppermute(dv_acc, axis_name, perm)
        return (dq_acc, k_next, v_next, dk_next, dv_next), None

    zeros3 = lambda: _pvary(jnp.zeros((B * H, Tl, D), jnp.float32), axis_name)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (zeros3(), k, v, zeros3(), zeros3()), jnp.arange(n))
    return (_from_bhtd(dq.astype(q.dtype), B, H),
            _from_bhtd(dk.astype(k.dtype), B, H),
            _from_bhtd(dv.astype(v.dtype), B, H))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_core(q, k, v, axis_name, causal, scale):
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, res, g):
    q, k, v, o_f, lse = res
    return _ring_bwd_impl(q, k, v, o_f, lse, g, axis_name, causal, scale)


_ring_core.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Ring attention over a sharded sequence axis.

    Call inside shard_map; q/k/v are the local (B, T/n, H, D) chunks of a
    globally (B, T, H, D) tensor sharded on `axis_name`.  Returns the local
    output chunk.  Equivalent to full softmax attention over the global
    sequence (verified against local_attention in tests).

    Both directions run the Pallas flash kernels per hop: the forward
    combines per-chunk (out, logsumexp) pairs; the backward is a second
    ring in which (dk, dv) accumulators rotate with their chunks.  Peak
    HBM is O(T/n · D) per chip in both directions — the T×T score matrix
    never exists, even at training time."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_core(q, k, v, axis_name, bool(causal), float(scale))


def _seq2head_impl(x, axis_name):
    # (B, Tl, H, D) -> (B, Tl, n, H/n, D) -> a2a over n -> (B, T, H/n, D)
    n = lax.psum(1, axis_name)
    B, Tl, H, D = x.shape
    x = x.reshape(B, Tl, n, H // n, D)
    x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=0,
                       tiled=False)
    # leading axis now n × B? all_to_all with split_axis=2, concat_axis=0
    # yields (n*B, Tl, H/n, D) — reorder to (B, n*Tl, H/n, D)
    x = x.reshape(n, B, Tl, H // n, D)
    x = x.transpose(1, 0, 2, 3, 4).reshape(B, n * Tl, H // n, D)
    return x


def _head2seq_impl(x, axis_name):
    # exact inverse of _seq2head_impl: (B, T, H/n, D) -> (B, Tl, H, D).
    # concat_axis=2 puts the gathered head-GROUP axis back in front of
    # the within-group axis, so the final reshape restores the original
    # head order h = group * (H/n) + i (concat_axis=3 — the historical
    # spelling — silently permuted heads whenever H/n > 1)
    n = lax.psum(1, axis_name)
    B, T, Hn, D = x.shape
    Tl = T // n
    x = x.reshape(B, n, Tl, Hn, D).transpose(1, 0, 2, 3, 4)
    x = lax.all_to_all(x.reshape(n, B, Tl, Hn, D), axis_name,
                       split_axis=0, concat_axis=2, tiled=False)
    return x.reshape(B, Tl, Hn * n, D)


# The two reshards are bijections (every element changes rank exactly
# once), so each one's VJP is simply the other applied to the cotangent
# — spelled as custom_vjp both because it is exact and because jax
# 0.4.x mis-shapes the transpose of the untiled all_to_all, which would
# otherwise make the Ulysses path untrainable.
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _seq2head(x, axis_name):
    return _seq2head_impl(x, axis_name)


def _seq2head_fwd(x, axis_name):
    return _seq2head_impl(x, axis_name), None


def _seq2head_bwd(axis_name, _res, g):
    return (_head2seq_impl(g, axis_name),)


_seq2head.defvjp(_seq2head_fwd, _seq2head_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _head2seq(x, axis_name):
    return _head2seq_impl(x, axis_name)


def _head2seq_fwd(x, axis_name):
    return _head2seq_impl(x, axis_name), None


def _head2seq_bwd(axis_name, _res, g):
    return (_seq2head_impl(g, axis_name),)


_head2seq.defvjp(_head2seq_fwd, _head2seq_bwd)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """All-to-all (Ulysses) sequence parallelism.

    Local chunks (B, T/n, H, D) are re-sharded to (B, T, H/n, D) with one
    all_to_all, attended fully per local head group, and re-sharded back.
    Requires H % n == 0.  Differentiable: the swap-back pair's VJPs are
    the inverse reshards, so forward+backward is 8 all_to_alls total —
    the ``ulysses_attention`` budget row pins exactly those bytes."""
    qg = _seq2head(q, axis_name)
    kg = _seq2head(k, axis_name)
    vg = _seq2head(v, axis_name)
    o = local_attention(qg, kg, vg, causal=causal, scale=scale)
    return _head2seq(o, axis_name)


def _seq_sharded_spec(mesh, axis):
    return NamedSharding(mesh, PartitionSpec(None, axis, None, None))


def _shard_map(fn, mesh, in_specs, out_specs, check=False):
    """Version-tolerant shard_map: jax>=0.5 exports jax.shard_map with a
    check_vma kwarg; 0.4.x has jax.experimental.shard_map with check_rep.
    check=False either way: the Pallas interpret-mode lowering slices
    blocks with non-varying program-id indices, which the replication/vma
    checker rejects; the kernels are correct under manual sharding."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check)


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False):
    """jit-able global entry: q/k/v are global (B, T, H, D) arrays; the
    function shards T over `axis` and runs ring attention."""
    spec = PartitionSpec(None, axis, None, None)
    fn = _shard_map(partial(ring_attention, axis_name=axis, causal=causal),
                    mesh, (spec, spec, spec), spec)
    return fn(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, axis="sp", causal=False):
    spec = PartitionSpec(None, axis, None, None)
    fn = _shard_map(partial(ulysses_attention, axis_name=axis,
                            causal=causal),
                    mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
