"""TPU-native parallelism: device meshes + GSPMD-sharded training.

This package is the TPU-first replacement for the reference's entire
distribution stack (SURVEY.md §2.2):

- ``DataParallelExecutorGroup`` batch slicing
  (``python/mxnet/module/executor_group.py:143``) → a ``jax.sharding.Mesh``
  with the batch sharded over the ``data`` axis; XLA's SPMD partitioner
  inserts the gradient ``psum`` over ICI automatically.
- ``KVStoreNCCL`` / ``Comm`` device reduce (``src/kvstore/kvstore_nccl.h``,
  ``src/kvstore/comm.h:451``) → the same psum; no user-visible allreduce.
- ``group2ctx`` model parallelism (``src/executor/graph_executor.cc:408``)
  → named mesh axes + per-parameter ``PartitionSpec`` rules; cross-device
  copies are implicit in GSPMD.
- ps-lite ``dist_sync`` (``src/kvstore/kvstore_dist.h``) → multi-host jax
  (``jax.distributed``) with the same mesh spanning DCN.

New-capability axes the reference lacks (documented in SURVEY.md §2.2):
tensor parallelism (shard params on a ``model`` axis), sequence
parallelism — ring attention over ``ppermute`` and Ulysses all-to-all
(``ring_attention.py``) — the ZeRO-1 sharded optimizer runtime
(``zero.py``, ``DataParallelTrainer(zero=1)``, docs/elastic.md), and
pipeline parallelism — stage-partitioned blocks over a ``pipe`` axis
running the microbatched 1F1B schedule (``pipeline.py``,
``MeshPlan(pipeline=K)``, docs/pipeline.md).
"""
from . import pipeline, zero
from .mesh import (make_mesh, data_parallel_mesh, local_device_count,
                   MeshPlan)
from .trainer import DataParallelTrainer
from .functional import functionalize_forward, functional_optimizer_update
from .ring_attention import (ring_attention, ulysses_attention,
                             local_attention, ring_attention_sharded,
                             ulysses_attention_sharded)

__all__ = [
    "pipeline", "zero", "make_mesh", "data_parallel_mesh",
    "local_device_count",
    "MeshPlan", "DataParallelTrainer", "functionalize_forward",
    "functional_optimizer_update", "ring_attention", "ulysses_attention",
    "local_attention", "ring_attention_sharded", "ulysses_attention_sharded",
]
