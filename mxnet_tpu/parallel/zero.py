"""ZeRO-1 sharded optimizer runtime (arxiv 2004.13336) for
``DataParallelTrainer(zero=1)``.

PR 11 proved the ZeRO-1 weight update *statically* (the
``zero1_mlp_train_step`` budget model and DST006-DST010); this module is
the runtime half.  The step is spelled **per replica** once and used two
ways, so the executed program and the analyzed program can never drift:

- **runtime**: the same per-replica functions run under ``shard_map``
  over the trainer's mesh as two jitted programs — ``grad_fn`` (forward
  + backward + reduce-scatter of the flat gradient) and ``update_fn``
  (shard-local optimizer update + all-gather of the new params).  The
  optimizer state lives as ONE flat ``(padded,)`` array per state leaf,
  sharded ``P(axis)`` over the data axis: each device physically holds
  ``1/K`` of it — the ZeRO-1 memory saving is real, not modeled.  The
  two-program split mirrors ``_dist_step``'s grad→exchange→update shape,
  which is what lets the performance doctor bill the reduce-scatter/
  all-gather program to the ``collective_or_ps`` phase.
- **analysis**: :func:`build_replica_step` composes the same two parts
  into one function traced with ``jax.make_jaxpr(axis_env=[(axis, K)])``
  — no devices — for the mxcost tape, the DST lint and the
  ``STATIC_BUDGETS.json`` runtime-parity checks
  (``analysis/budget_models.zero1_mlp_train_step``).

Flat layout: every trainable parameter raveled (f32) and concatenated in
``collect_params`` order, zero-padded to a multiple of K.  Rank ``r``
owns the contiguous ``[r*shard, (r+1)*shard)`` slice of that flat space
— ``psum_scatter`` lands exactly the owned gradient shard, the update is
shard-local, ``all_gather(tiled=True)`` reassembles the flat vector.
The padding tail provably stays zero across steps (gradients pad with
zeros, so every elementwise optimizer maps a zero (w, g, state) tail to
a zero tail), which is what makes resize-on-resume checkpointing exact:
a shard set saved at fleet size K truncates to the unpadded ``total``
and re-pads for any other size bitwise-losslessly
(``resilience/checkpoint.py`` sharded snapshots, docs/elastic.md).

``ZERO1_RUNTIME_ALL_GATHER`` is the runtime mutation seam (the
shard-fixture ``ZERO1_ALL_GATHER`` discipline): tests flip it from a
subprocess to prove that deleting the runtime all-gather fails the
``STATIC_BUDGETS.json`` gate with DST007 named.  Production code never
touches it.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["ZERO1_RUNTIME_ALL_GATHER", "Zero1Plan", "build_parts",
           "build_replica_step", "build_runtime_fns", "reassemble_state",
           "reshard_full"]

# runtime mutation seam (see module docstring) — flipped only by tests
ZERO1_RUNTIME_ALL_GATHER = True


class Zero1Plan:
    """The flat parameter layout of one ZeRO-1 trainer over ``axis``.

    Pure shapes arithmetic (no jax): names/shapes/dtypes in parameter
    order, the flat ``total``, the K-padded length and the per-rank
    ``shard`` size.  Deterministic given (parameters, K) — both the
    runtime and the resize-on-resume restore path derive their slicing
    from it, so a fleet of a different size re-shards identically.
    """

    def __init__(self, names, shapes, dtypes, axis, k):
        self.names = list(names)
        self.shapes = [tuple(int(d) for d in s) for s in shapes]
        self.dtypes = [str(d) for d in dtypes]
        self.axis = str(axis)
        self.k = int(k)
        if self.k < 1:
            raise ValueError("zero=1 needs a data axis of size >= 1, "
                             "got %d" % self.k)
        self.sizes = [int(_np.prod(s)) if s else 1 for s in self.shapes]
        self.total = int(sum(self.sizes))
        self.padded = -(-self.total // self.k) * self.k
        self.shard = self.padded // self.k

    def describe(self):
        """JSON-able layout record embedded in sharded checkpoints so a
        restore at a different fleet size can re-derive the slicing."""
        return {"names": list(self.names), "shapes": [list(s) for s in
                                                      self.shapes],
                "dtypes": list(self.dtypes), "axis": self.axis,
                "k": self.k, "total": self.total, "padded": self.padded,
                "shard": self.shard}


def _flatten_pad(vals, plan, jnp, dtype=None):
    dtype = jnp.float32 if dtype is None else dtype
    parts = [v.ravel().astype(dtype) for v in vals]
    pad = plan.padded - plan.total
    if pad:
        parts.append(jnp.zeros((pad,), dtype))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _unflatten(flat, plan, jnp):
    out, off = [], 0
    for shape, size, dt in zip(plan.shapes, plan.sizes, plan.dtypes):
        out.append(flat[off:off + size].reshape(shape)
                   .astype(_np.dtype(dt)))
        off += size
    return tuple(out)


def build_parts(fwd, opt, plan, state_treedef, compute_dtype=None,
                grad_accum=1):
    """``(grads_part, update_part)`` — the per-replica halves of the
    ZeRO-1 step.  Both are pure jax functions over LOCAL shards (the
    ``shard_map`` / ``axis_env`` view):

    - ``grads_part(train_vals, aux_vals, x, y, key) -> (g_shard, loss,
      muts)``: forward + backward on the local batch shard, flat
      gradient reduce-scattered over ``plan.axis`` (mean), loss and
      BatchNorm batch statistics pmean'd — the step's ONE gradient
      reduction point (DST001/DST006 subject).
    - ``update_part(train_vals, state_leaves, g_shard, lr, t) ->
      (new_vals, new_state_leaves)``: the rank's flat weight shard
      sliced out, the SAME ``Optimizer.update`` code as the eager path
      applied shard-locally, the new params all-gathered back whole
      (the DST007 pair).

    With ``compute_dtype=bfloat16`` (``mxnet_tpu.precision``,
    docs/precision.md) the halves grow the mixed-precision signature
    instead: params/activations are bf16, the f32 MASTER weights live
    only as the ``(shard,)`` slice each rank owns (they never
    materialize unsharded — the arxiv 2004.13336 layout), gradients are
    cast f32 BEFORE the reduce-scatter (the tightened DST004 subject),
    the loss-scale grow/backoff tick and the inf/nan select-skip ride
    the update, and the all-gather reassembles the params ALREADY cast
    bf16 — half the wire and param-HBM bytes:

    - ``grads_part(train_vals, aux_vals, x, y, key, scale) ->
      (g_shard_f32, loss, muts, grads_finite)``
    - ``update_part(train_vals, master_shard, state_leaves, g_shard,
      lr, t, scale, good_steps, skipped, grads_finite) ->
      (new_vals_bf16, new_master_shard, new_state_leaves, new_scale,
      new_good_steps, new_skipped)``
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .functional import functional_optimizer_update

    axis, k, shard = plan.axis, plan.k, plan.shard

    if compute_dtype is not None and \
            jnp.dtype(compute_dtype) != jnp.float32:
        if int(grad_accum or 1) > 1:
            raise ValueError("grad_accum is not supported with a "
                             "reduced compute dtype (see "
                             "DataParallelTrainer)")
        return _build_parts_reduced(fwd, opt, plan, state_treedef,
                                    jnp.dtype(compute_dtype))

    n_acc = int(grad_accum or 1)
    if n_acc > 1:
        # grad_accum spelling (docs/distributed.md): the shard-local
        # batch splits into microbatches accumulated left-to-right
        # (functional.accumulate_grads — the SAME helper the replicated
        # trainer jits), then ONE reduce-scatter of the summed flat
        # gradient: the collective count and wire bytes are unchanged
        # vs n_acc=1, which keeps DST006's one-reduction contract
        from .functional import accumulate_grads

        def grads_part(train_vals, aux_vals, x, y, key):
            def grad_of(tv, xi, yi):
                def loss_of(t_):
                    outs, muts = fwd(t_, aux_vals, (xi, yi), key)
                    return outs[0], muts
                return jax.value_and_grad(loss_of, has_aux=True)(tv)

            grads_sum, loss_sum, muts_stack = accumulate_grads(
                grad_of, train_vals, x, y, n_acc)
            grads = tuple(g / n_acc for g in grads_sum)
            flat_g = _flatten_pad(grads, plan, jnp)
            g_sh = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                                    tiled=True) / k
            loss_val = lax.pmean(loss_sum / n_acc, axis)
            muts = tuple(lax.pmean(m.mean(axis=0), axis)
                         for m in muts_stack)
            return g_sh, loss_val, muts
    else:
        def grads_part(train_vals, aux_vals, x, y, key):
            def loss_of(tv):
                outs, muts = fwd(tv, aux_vals, (x, y), key)
                return outs[0], muts

            (loss_val, muts), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            flat_g = _flatten_pad(grads, plan, jnp)
            # reduce-scatter lands exactly this rank's owned gradient
            # shard; /k turns the psum semantics into the gradient mean
            # every replicated spelling uses
            g_sh = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                                    tiled=True) / k
            loss_val = lax.pmean(loss_val, axis)
            muts = tuple(lax.pmean(m, axis) for m in muts)
            return g_sh, loss_val, muts

    def update_part(train_vals, state_leaves, g_sh, lr, t):
        from ..ops import fused_optimizer as _fused

        flat_w = _flatten_pad(train_vals, plan, jnp)
        idx = lax.axis_index(axis)
        w_sh = lax.dynamic_slice(flat_w, (idx * shard,), (shard,))
        state = jax.tree_util.tree_unflatten(state_treedef,
                                             list(state_leaves))
        if _fused.fused_update_enabled() and _fused.supports(opt):
            # the rs → FUSED-update → ag spelling (docs/fusion.md): the
            # shard-local optimizer chain runs as one Pallas pass over
            # the owned 1/K slice; state stays physically sharded and
            # the kernel's numerics mirror Optimizer.update exactly
            new_w_sh, new_state = _fused.fused_optimizer_update(
                opt, 0, w_sh, g_sh, state, lr, t)
        else:
            new_w_sh, new_state = functional_optimizer_update(
                opt, 0, w_sh, g_sh, state, lr, t)
        if ZERO1_RUNTIME_ALL_GATHER:
            new_flat = lax.all_gather(new_w_sh, axis, tiled=True)
        else:
            # the classic broken spelling (tests only): the rank's own
            # shard tiled out as if it were the gathered whole — every
            # rank's params become mostly some other rank's bytes
            new_flat = jnp.concatenate([new_w_sh] * k) if k > 1 \
                else new_w_sh
        new_vals = _unflatten(new_flat, plan, jnp)
        return new_vals, tuple(jax.tree_util.tree_leaves(new_state))

    return grads_part, update_part


def _build_parts_reduced(fwd, opt, plan, state_treedef, compute_dtype):
    """The mixed-precision halves (see :func:`build_parts` docstring):
    bf16 compute, f32 masters-in-the-shard, f32 gradient reduction,
    loss scaling with select-skip.  Split out so the f32 spelling's
    traced program stays byte-identical."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .. import precision as _prec
    from .functional import functional_optimizer_update

    axis, k, shard = plan.axis, plan.k, plan.shard

    def _to_compute(v):
        # only floating leaves move to bf16 — integer labels/ids stay put
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(compute_dtype)
        return v

    def grads_part(train_vals, aux_vals, x, y, key, scale):
        # the batch and any floating aux enter the forward in the
        # compute dtype too, else f32 inputs silently promote the
        # activations back to f32 and the bytes win evaporates
        x_c = _to_compute(x)
        aux_c = tuple(_to_compute(a) for a in aux_vals)

        def loss_of(tv):
            outs, muts = fwd(tv, aux_c, (x_c, y), key)
            raw = outs[0].astype(jnp.float32)
            # the SCALED loss drives the backward so bf16 grads don't
            # flush; the raw loss rides aux for reporting
            return raw * scale, (raw, muts)

        (_, (loss_val, muts)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(train_vals)
        if _prec.PRECISION_F32_GRAD_REDUCE:
            # cast BEFORE the collective: the ring reduction must run
            # f32 (the tightened DST004 contract, docs/precision.md)
            flat_g = _flatten_pad(grads, plan, jnp)
            g_sh = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                                    tiled=True) / k
        else:
            # the seam's broken spelling (tests only): reduce in bf16
            # and widen after — exactly what DST004 must catch
            flat_g = _flatten_pad(grads, plan, jnp, compute_dtype)
            g_sh = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                                    tiled=True).astype(jnp.float32) / k
        # global inf/nan verdict: every rank checks its owned shard,
        # pmin ANDs the flags (1.0 = every gradient element finite)
        fin = lax.pmin(
            jnp.isfinite(g_sh).all().astype(jnp.float32), axis)
        loss_val = lax.pmean(loss_val, axis)
        muts = tuple(lax.pmean(m.astype(jnp.float32), axis)
                     for m in muts)
        return g_sh, loss_val, muts, fin

    def update_part(train_vals, master_sh, state_leaves, g_sh, lr, t,
                    scale, good, skipped, fin):
        from ..ops import fused_optimizer as _fused

        if _prec.PRECISION_MASTER_F32:
            # the masters ARE the shard: each rank updates the f32
            # slice it owns; no flat f32 weight vector ever exists
            w_sh = master_sh
        else:
            # the seam's broken spelling (tests only): "masters"
            # re-derived from the bf16 params — the full flat f32
            # space materializes per rank and the master precision is
            # lost, which the bf16_zero1_train_step peak-HBM/precision
            # proof must catch (COST001 rc=2)
            flat_w = _flatten_pad(train_vals, plan, jnp)
            idx = lax.axis_index(axis)
            w_sh = lax.dynamic_slice(flat_w, (idx * shard,), (shard,))
        inv = (1.0 / scale).astype(jnp.float32)
        state = jax.tree_util.tree_unflatten(state_treedef,
                                             list(state_leaves))
        if _fused.fused_update_enabled() and _fused.supports(opt):
            # unscale + clip + update + select-skip as ONE kernel pass:
            # the loss-scale reciprocal and the finite flag ride the
            # SMEM scalar block (docs/fusion.md, docs/precision.md)
            new_w_sh, new_state = _fused.fused_optimizer_update(
                opt, 0, w_sh, g_sh, state, lr, t, inv_scale=inv,
                ok=fin)
        else:
            nw, ns = functional_optimizer_update(
                opt, 0, w_sh, g_sh * inv, state, lr, t)
            okb = fin > 0.0
            new_w_sh = jnp.where(okb, nw, w_sh)
            new_state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(okb, n, o), ns, state)
        new_scale, new_good = _prec.loss_scale_update(scale, good,
                                                      fin > 0.0)
        new_skipped = skipped + (1 - fin.astype(jnp.int32))
        # cast BEFORE the gather: the reassembled params are bf16, so
        # the all-gather moves half the wire bytes and the gathered
        # param copy holds half the HBM of the f32 twin
        new_w_c = new_w_sh.astype(compute_dtype)
        if ZERO1_RUNTIME_ALL_GATHER:
            new_flat = lax.all_gather(new_w_c, axis, tiled=True)
        else:
            # the classic broken spelling (tests only; see build_parts)
            new_flat = jnp.concatenate([new_w_c] * k) if k > 1 \
                else new_w_c
        new_vals = _unflatten(new_flat, plan, jnp)
        return (new_vals, new_w_sh,
                tuple(jax.tree_util.tree_leaves(new_state)),
                new_scale, new_good, new_skipped)

    return grads_part, update_part


def build_replica_step(fwd, opt, plan, state_treedef,
                       compute_dtype=None, grad_accum=1):
    """One per-replica function composing both halves — the analysis
    spelling.  ``step(train_vals, state_leaves, aux_vals, x, y, key,
    lr, t) -> (loss, new_vals, new_state_leaves, muts)``; trace with
    ``jax.make_jaxpr(axis_env=[(plan.axis, plan.k)])``.

    Under a reduced ``compute_dtype`` the spelling grows the
    mixed-precision arguments instead (the :func:`build_parts`
    docstring): ``step(train_vals, master_sh, state_leaves, aux_vals,
    x, y, key, lr, t, scale, good, skipped) -> (loss, new_vals,
    new_master_sh, new_state_leaves, muts, new_scale, new_good,
    new_skipped)``."""
    import jax.numpy as jnp

    grads_part, update_part = build_parts(fwd, opt, plan, state_treedef,
                                          compute_dtype=compute_dtype,
                                          grad_accum=grad_accum)
    if compute_dtype is not None and \
            jnp.dtype(compute_dtype) != jnp.float32:
        def replica_step(train_vals, master_sh, state_leaves, aux_vals,
                         x, y, key, lr, t, scale, good, skipped):
            g_sh, loss_val, muts, fin = grads_part(
                train_vals, aux_vals, x, y, key, scale)
            (new_vals, new_master, new_states, new_scale, new_good,
             new_skipped) = update_part(
                train_vals, master_sh, state_leaves, g_sh, lr, t,
                scale, good, skipped, fin)
            return (loss_val, new_vals, new_master, new_states, muts,
                    new_scale, new_good, new_skipped)

        return replica_step

    def replica_step(train_vals, state_leaves, aux_vals, x, y, key,
                     lr, t):
        g_sh, loss_val, muts = grads_part(train_vals, aux_vals, x, y,
                                          key)
        new_vals, new_states = update_part(train_vals, state_leaves,
                                           g_sh, lr, t)
        return loss_val, new_vals, new_states, muts

    return replica_step


def build_runtime_fns(fwd, opt, plan, state_treedef, mesh,
                      compute_dtype=None, grad_accum=1):
    """``(grad_fn, update_fn)`` — the jitted ``shard_map`` programs the
    trainer dispatches each step.  ``grad_fn``'s flat-gradient output
    and the optimizer-state leaves are GLOBAL ``(padded,)`` arrays
    sharded ``P(axis)`` (each device holds its ``shard``-sized slice);
    params/aux/loss stay replicated; the batch shards over ``axis``.
    ``update_fn`` donates params, states and the gradient shard, so the
    update happens in place in HBM exactly like the fused step.

    Under a reduced ``compute_dtype`` the f32 master shard is an extra
    GLOBAL ``(padded,)`` ``P(axis)`` array threaded through ``update_fn``
    (donated in, returned out) and the loss-scale scalars ride
    replicated — the :func:`build_parts` mixed-precision signature."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .ring_attention import _shard_map

    grads_part, update_part = build_parts(fwd, opt, plan, state_treedef,
                                          compute_dtype=compute_dtype,
                                          grad_accum=grad_accum)
    axis = plan.axis
    if compute_dtype is not None and \
            jnp.dtype(compute_dtype) != jnp.float32:
        grad_fn = jax.jit(_shard_map(
            grads_part, mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(), P()),
            out_specs=(P(axis), P(), P(), P())))
        update_fn = jax.jit(_shard_map(
            update_part, mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(), P(), P(),
                      P(), P(), P()),
            out_specs=(P(), P(axis), P(axis), P(), P(), P())),
            donate_argnums=(0, 1, 2, 3))
        return grad_fn, update_fn
    grad_fn = jax.jit(_shard_map(
        grads_part, mesh,
        in_specs=(P(), P(), P(axis), P(axis), P()),
        out_specs=(P(axis), P(), P())))
    update_fn = jax.jit(_shard_map(
        update_part, mesh,
        in_specs=(P(), P(axis), P(axis), P(), P()),
        out_specs=(P(), P(axis))), donate_argnums=(0, 1, 2))
    return grad_fn, update_fn


def reassemble_state(shard_arrays, total):
    """Concatenate one state leaf's per-rank shards (save-time order)
    and truncate the padding tail -> the exact ``(total,)`` full leaf.
    Lossless: the tail is provably zero (module docstring)."""
    full = _np.concatenate([_np.asarray(a).ravel() for a in shard_arrays])
    if full.shape[0] < total:
        raise ValueError("shards hold %d elements, need %d"
                         % (full.shape[0], total))
    return full[:total]


def reshard_full(full, k):
    """Deterministically re-shard one full ``(total,)`` leaf for a fleet
    of size ``k``: zero-pad to the new K-multiple and split into K equal
    contiguous shards.  ``reassemble_state(reshard_full(x, k), len(x))``
    is the identity for every k — the 1→2→4→1 bitwise round-trip."""
    full = _np.asarray(full).ravel()
    total = full.shape[0]
    padded = -(-total // int(k)) * int(k)
    if padded != total:
        full = _np.concatenate(
            [full, _np.zeros((padded - total,), full.dtype)])
    shard = padded // int(k)
    return [full[r * shard:(r + 1) * shard] for r in range(int(k))]
