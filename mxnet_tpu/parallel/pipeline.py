"""Microbatched 1F1B pipeline schedule over the ``pipe`` mesh axis.

The fourth ``MeshPlan`` axis (docs/pipeline.md): transformer blocks are
stage-partitioned over ``pipe`` — each stage holds ``n_layers / K``
blocks as the leading dim of stacked ``blk_*`` parameters — and one
train step runs the batch as ``M`` microbatches through a scanned
schedule whose only cross-stage traffic is one ``ppermute`` hop of the
activation per tick.

The schedule is spelled ONCE here, as :func:`pipeline_loss`, and
consumed by both the jitted ``shard_map`` runtime and the
``make_jaxpr(axis_env)`` analysis (the ``parallel/zero.py``
discipline), so the executed schedule and the modeled one — per-hop
ppermute bytes, tick count ``M + K - 1``, bubble fraction
``(K-1)/(K-1+M)`` — can never drift.

How the single-program spelling works: every stage runs the SAME
scanned loop for ``M + K - 1`` ticks.  Stage 0 ingests microbatch
``min(t, M-1)`` through ``embed_fn`` at tick ``t`` (masked by
``axis_index == 0``); every other stage takes the activation its
predecessor ``ppermute``'d last tick; the last stage scores microbatch
``t - (K-1)`` through ``head_fn`` once ``t >= K-1`` (masked likewise).
Warm-up/drain ticks run on zero activations and are masked out of the
loss — that wasted work is exactly the pipeline bubble, and because the
mask is data-independent the modeled fraction is the classic
``(K-1)/(K-1+M)``.  Autodiff of the scan yields the reverse schedule
for free: the backward pass replays the ticks with the inverse
``ppermute`` ring carrying cotangents upstream, and the stacked scan
residuals ARE the activation stash — peak HBM grows with the in-flight
microbatch count, which is what the DST011 liveness rule pins.

Gradients: stage-local (``blk_*``) parameter gradients are complete per
stage and are reduced over the batch axes ONLY — a reduction over
``pipe`` would mix gradients of DIFFERENT layers (DST012).  The few
pipe-replicated parameters (embedding, final norm, output head) get
partial gradients on the stages that touch them and exact zeros
elsewhere, so their one ``psum`` over ``pipe`` in
:func:`reduce_replicated_grads` completes them.
"""
from __future__ import annotations

__all__ = ["PP_GRAD_ACCUM", "bubble_fraction", "pipeline_ticks",
           "pipeline_loss", "reduce_replicated_grads"]

# Mutation seam (docs/analysis.md): the classic broken pipeline "sync"
# — treating ``pipe`` as one more data axis and averaging stage-local
# gradients over it, which mixes gradients of DIFFERENT layers into
# every stage's update.  False swaps in that spelling; the DST012
# taint lint and the pp numerics gate must both catch it.
PP_GRAD_ACCUM = True


def bubble_fraction(k, m):
    """Modeled idle fraction of the 1F1B schedule: ``K - 1`` of the
    ``M + K - 1`` ticks are warm-up/drain on any given stage."""
    k, m = int(k), int(m)
    return float(k - 1) / float(k - 1 + m)


def pipeline_ticks(k, m):
    """Scan length of the schedule: every microbatch plus the fill."""
    return int(m) + int(k) - 1


def pipeline_loss(embed_fn, stage_fn, head_fn, x, y, plan, n_micro,
                  act_dtype, axis="pipe"):
    """Mean causal-LM loss of the LOCAL batch, computed by the 1F1B
    schedule (module docstring).  ``embed_fn(x_mb) -> (mb, t, d)``
    lifts a microbatch of tokens onto the residual stream (stage 0
    only); ``stage_fn(h) -> h`` applies this stage's blocks;
    ``head_fn(h, y_mb) -> scalar`` scores the last stage's output.
    All three close over this replica's local parameter shards, so the
    model/sequence collectives they contain ride along unchanged —
    pipeline composes with TP/SP by construction.

    Returns the full-batch mean loss, identical on every stage (the
    forward ``psum`` over ``pipe`` is a ``custom_vjp`` completion with
    identity backward, the ``complete_psum`` idiom of
    ``transformer/layers.py``)."""
    import jax.numpy as jnp
    from jax import lax

    from ..transformer.layers import complete_psum

    k = plan.size(axis)
    n_micro = int(n_micro)
    b, t_local = x.shape[0], x.shape[1]
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1, got %d" % n_micro)
    if b % n_micro:
        raise ValueError(
            "local batch %d must divide into %d microbatches" %
            (b, n_micro))
    mb = b // n_micro
    ticks = pipeline_ticks(k, n_micro)
    xm = x.reshape(n_micro, mb, t_local)
    ym = y.reshape(n_micro, mb, t_local)
    r = lax.axis_index(axis)
    # one full single-cycle ring: stage i hands its activation to i+1;
    # the wrap-around edge only ever carries masked-out garbage
    perm = [(i, (i + 1) % k) for i in range(k)]

    def tick(carry, t):
        recv = carry
        in_idx = jnp.clip(t, 0, n_micro - 1)
        emb = embed_fn(xm[in_idx])
        inp = jnp.where(r == 0, emb, recv)
        out = stage_fn(inp)
        out_idx = t - (k - 1)
        mb_loss = head_fn(out, ym[jnp.clip(out_idx, 0, n_micro - 1)])
        valid = (r == k - 1) & (out_idx >= 0)
        loss_inc = jnp.where(valid, mb_loss, jnp.zeros_like(mb_loss))
        nxt = lax.ppermute(out, axis, perm)
        return nxt, loss_inc

    init = jnp.zeros((mb, t_local, _embed_width(embed_fn, xm)),
                     act_dtype)
    _, losses = lax.scan(tick, init, jnp.arange(ticks))
    # each microbatch contributes its own mean; microbatches are equal
    # sized, so the mean of means is the full local-batch mean
    loss_local = losses.sum() / n_micro
    return complete_psum(loss_local, plan, axis=axis)


def _embed_width(embed_fn, xm):
    """Residual width of ``embed_fn``'s output, resolved at trace time
    so the scan carry matches without running the embedding twice."""
    import jax

    shape = jax.eval_shape(embed_fn, xm[0]).shape
    return shape[-1]


def reduce_replicated_grads(grads, param_names, replicated_names,
                            axis="pipe"):
    """The step's ONE ``pipe``-axis gradient exchange: complete the
    pipe-replicated parameters' partial gradients (each stage
    contributed its own term or exact zeros) with a ``psum``.
    Stage-local ``blk_*`` gradients pass through untouched — reducing
    them over ``pipe`` would mix gradients of different layers
    (DST012), which is exactly what the ``PP_GRAD_ACCUM=False`` broken
    spelling below does."""
    from jax import lax

    out = []
    for name, g in zip(param_names, grads):
        if name in replicated_names:
            g = lax.psum(g, axis)
        elif not PP_GRAD_ACCUM:
            # classic broken spelling (tests only): "synchronize" the
            # stage-local gradients like a data axis — every stage now
            # updates its blocks with an average over DIFFERENT layers
            g = lax.pmean(g, axis)
        out.append(g)
    return tuple(out)
