"""Functionalization bridge: stateful Gluon blocks / MXNet optimizers → pure
jax functions suitable for ``jax.jit`` over a sharded mesh.

The reference never needs this layer because its executors mutate buffers in
place under the dependency engine (``src/executor/graph_executor.cc``,
``src/operator/optimizer_op-inl.h``); XLA instead wants a pure
``(params, batch) -> (loss, new_params)`` program so it can plan buffers,
donate inputs, and insert collectives.  The same Python ``Optimizer.update``
code that drives the eager path is traced here with its NDArray mutations
captured — one numerics codebase for both paths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import autograd, _rng
from ..ndarray import NDArray
from ..ndarray import ndarray as _ndmod

__all__ = ["functionalize_forward", "functional_optimizer_update",
           "accumulate_grads", "state_to_raw", "tree_raw"]


def tree_raw(x):
    """Recursively unwrap NDArrays in a None/NDArray/tuple/list/dict pytree."""
    if x is None:
        return None
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (tuple, list)):
        return tuple(tree_raw(v) for v in x)
    if isinstance(x, dict):
        return {k: tree_raw(v) for k, v in x.items()}
    return x


def _tree_wrap(x):
    if x is None:
        return None
    if isinstance(x, (tuple, list)):
        return tuple(_tree_wrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _tree_wrap(v) for k, v in x.items()}
    return NDArray(x)


def _tree_unwrap_updated(wrapped):
    """Read back possibly-mutated NDArray handles into raw values."""
    if wrapped is None:
        return None
    if isinstance(wrapped, (tuple, list)):
        return tuple(_tree_unwrap_updated(v) for v in wrapped)
    if isinstance(wrapped, dict):
        return {k: _tree_unwrap_updated(v) for k, v in wrapped.items()}
    return wrapped._data


state_to_raw = tree_raw


def functionalize_forward(run, params_by_name, train_names, aux_names,
                          train=True):
    """Build a pure fn ``(train_vals, aux_vals, input_vals, key) ->
    (output_vals, mut_aux_vals)`` from an eager callable ``run(*inputs)``
    that reads the given Parameters.

    ``run`` is executed with the parameters' backing arrays swapped for
    tracers and NDArray mutations captured — the functional analogue of
    FMutateInputs (``include/mxnet/op_attr_types.h``), used for BatchNorm
    moving stats.  The mutated-aux name list is recorded on the returned
    function as ``.mut_names`` at first trace.
    """
    all_names = list(train_names) + list(aux_names)

    def pure(train_vals, aux_vals, input_vals, rng_key):
        vals = list(train_vals) + list(aux_vals)
        mutations = []
        _ndmod._MUTATION_TRACKERS.append(
            lambda obj, val: mutations.append((obj, val)))
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(train)
        saved = {}
        try:
            with _rng.trace_scope(rng_key):
                for name, val in zip(all_names, vals):
                    saved[name] = params_by_name[name]._data._data
                    params_by_name[name]._data._data = val
                try:
                    wrapped = [NDArray(v) for v in input_vals]
                    out = run(*wrapped)
                finally:
                    mut_names, mut_vals = [], []
                    for obj, new_val in mutations:
                        for name in all_names:
                            if params_by_name[name]._data is obj:
                                mut_names.append(name)
                                mut_vals.append(new_val)
                                break
                    for name in all_names:
                        params_by_name[name]._data._data = saved[name]
        finally:
            _ndmod._MUTATION_TRACKERS.pop()
            autograd.set_recording(prev_rec)
            autograd.set_training(prev_train)
        single = not isinstance(out, (list, tuple))
        outs = [out] if single else list(out)
        pure.mut_names = mut_names
        pure.single = single
        return tuple(o._data for o in outs), tuple(mut_vals)

    pure.mut_names = None
    pure.single = True
    return pure


def accumulate_grads(grad_of, train_vals, x, y, n_acc):
    """Left-fold microbatch gradient accumulation — the ONE spelling
    behind ``DataParallelTrainer(grad_accum=N)`` (docs/distributed.md),
    shared by the replicated jitted step, its per-replica analysis twin,
    and the ZeRO-1 grads half so runtime and analyzed tape cannot drift.

    ``grad_of(train_vals, x_micro, y_micro) -> ((loss, muts), grads)``
    is the per-microbatch ``value_and_grad`` closure.  The batch's
    leading dim splits into ``n_acc`` equal microbatches scanned in
    order, gradients summed left-to-right: the accumulated gradient is
    bitwise equal to summing independently computed per-microbatch
    gradients in the same order (fp addition is deterministic — only
    the grouping is pinned; it is NOT bitwise vs the large-batch step,
    whose loss mean reassociates the sum).

    Returns ``(grads_sum, loss_sum, muts_stack)``: the caller divides
    by ``n_acc`` for the batch mean and reduces the ``(n_acc,)``-stacked
    mutation leaves (the trainer averages them, the batch-stat analogue
    of the loss mean).
    """
    n = int(n_acc)
    b = x.shape[0]
    if n <= 1:
        (loss_val, muts), grads = grad_of(train_vals, x, y)
        return grads, loss_val, tuple(m[None] for m in muts)
    if b % n:
        raise ValueError(
            "grad_accum=%d does not divide the (per-replica) batch %d: "
            "microbatches must be equal-sized for the accumulated mean "
            "to equal the batch mean" % (n, b))
    xm = x.reshape((n, b // n) + tuple(x.shape[1:]))
    ym = y.reshape((n, b // n) + tuple(y.shape[1:]))

    def body(carry, xy):
        acc, loss_sum = carry
        (loss_val, muts), grads = grad_of(train_vals, xy[0], xy[1])
        acc = tuple(a + g for a, g in zip(acc, grads))
        return (acc, loss_sum + loss_val), muts

    zeros = tuple(jnp.zeros_like(w) for w in train_vals)
    (grads_sum, loss_sum), muts_stack = jax.lax.scan(
        body, (zeros, jnp.zeros((), jnp.float32)), (xm, ym))
    return grads_sum, loss_sum, muts_stack


def functional_optimizer_update(opt, index, weight_val, grad_val, state_raw,
                                lr_val, t_val):
    """Trace one ``Optimizer.update`` call as a pure function.

    ``lr_val`` (learning rate, host-computed — schedulers use Python control
    flow) and ``t_val`` (update count, for Adam-style bias correction) enter
    as traced scalars so one compiled program serves every step; the
    reference instead re-reads these host-side each iteration
    (``python/mxnet/optimizer.py`` ``_get_lr``/``_update_count``).
    Returns ``(new_weight_val, new_state_raw)``.
    """
    w = NDArray(weight_val)
    g = NDArray(grad_val)
    state = _tree_wrap(state_raw)

    saved = (opt.lr, opt.lr_scheduler, opt._index_update_count.get(index),
             opt.num_update)
    opt.lr = lr_val
    opt.lr_scheduler = None
    # _update_count would do python `max` on tracers; pin counts directly.
    opt._index_update_count[index] = t_val
    saved_uc = opt._update_count
    opt._update_count = lambda _idx: None
    try:
        opt.update_multi_precision(index, w, g, state)
    finally:
        opt._update_count = saved_uc
        opt.lr, opt.lr_scheduler = saved[0], saved[1]
        if saved[2] is None:
            opt._index_update_count.pop(index, None)
        else:
            opt._index_update_count[index] = saved[2]
        opt.num_update = saved[3]
    return w._data, _tree_unwrap_updated(state)
