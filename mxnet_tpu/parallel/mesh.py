"""Device-mesh construction helpers.

The mesh is the TPU analogue of the reference's context list
(``ctx=[mx.gpu(i) for i in ...]`` handed to Module/Trainer): instead of one
executor per device with explicit gradient reduction, every jitted program
spans the whole mesh and XLA lowers the sharding annotations to ICI/DCN
collectives.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "data_parallel_mesh", "local_device_count",
           "replicated", "batch_sharded", "Mesh", "NamedSharding",
           "PartitionSpec"]


def local_device_count():
    return jax.local_device_count()


def make_mesh(shape=None, axis_names=("data",), devices=None):
    """Build a Mesh.  ``shape`` is a tuple matching ``axis_names``;
    default: all devices on one ``data`` axis (pure DP)."""
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError("mesh shape %r needs %d devices, have %d"
                         % (shape, n, len(devices)))
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, axis_names)


def data_parallel_mesh(num=None):
    devices = jax.devices()
    if num is not None:
        devices = devices[:num]
    return make_mesh((len(devices),), ("data",), devices)


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh, axis="data"):
    """Sharding for a batch tensor: leading dim split on ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))
