"""Device-mesh construction helpers.

The mesh is the TPU analogue of the reference's context list
(``ctx=[mx.gpu(i) for i in ...]`` handed to Module/Trainer): instead of one
executor per device with explicit gradient reduction, every jitted program
spans the whole mesh and XLA lowers the sharding annotations to ICI/DCN
collectives.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "data_parallel_mesh", "local_device_count",
           "replicated", "batch_sharded", "MeshPlan", "Mesh",
           "NamedSharding", "PartitionSpec"]


def local_device_count():
    return jax.local_device_count()


def make_mesh(shape=None, axis_names=("data",), devices=None):
    """Build a Mesh.  ``shape`` is a tuple matching ``axis_names``;
    default: all devices on one ``data`` axis (pure DP)."""
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError("mesh shape %r needs %d devices, have %d"
                         % (shape, n, len(devices)))
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, axis_names)


def data_parallel_mesh(num=None):
    devices = jax.devices()
    if num is not None:
        devices = devices[:num]
    return make_mesh((len(devices),), ("data",), devices)


class MeshPlan:
    """A 2-4D mesh as pure declaration:
    ``data × model × sequence × pipe``.

    The multi-axis tier's single source of truth (docs/transformer.md):
    the same plan drives the runtime ``Mesh`` construction, the
    ``shard_map`` partition specs, and the hardware-free analysis
    (``MeshSpec`` via :meth:`axis_sizes`, ``make_jaxpr(axis_env=...)``
    via :meth:`axis_env`).  Any axis of size 1 **collapses**: it is
    absent from the built mesh, from every partition spec and from every
    collective — a ``MeshPlan(model=2)`` program contains no sequence
    collectives at all, not degenerate 1-member ones.

    ``pipeline=K`` arms the fourth axis (docs/pipeline.md): transformer
    blocks are stage-partitioned over ``pipe`` and the step runs the
    microbatched 1F1B schedule of ``parallel/pipeline.py`` with
    ``ppermute`` stage-boundary activation transfers.  ``pipe`` is
    never a batch axis: gradients of stage-local parameters are
    reduced over ``data``/``sequence`` only (DST012).

    ``data=None`` defers the data-axis size to :meth:`resolve` (fill
    with whatever devices remain after ``model × sequence × pipe``), so
    a plan can be declared before a backend exists — the analysis path
    never needs devices.
    """

    AXES = ("data", "model", "sequence", "pipe")

    def __init__(self, data=None, model=1, sequence=1, pipeline=1):
        self.data = None if data is None else int(data)
        self.model = int(model)
        self.sequence = int(sequence)
        self.pipe = int(pipeline)
        for name in ("data", "model", "sequence", "pipe"):
            v = getattr(self, name)
            if v is not None and v < 1:
                raise ValueError("MeshPlan axis %r must be >= 1, got %r"
                                 % (name, v))

    @classmethod
    def coerce(cls, plan):
        """A MeshPlan from a MeshPlan / dict /
        (data, model, sequence[, pipeline]) tuple — the
        ``DataParallelTrainer(mesh_plan=...)`` accessor.  Dicts accept
        ``pipeline`` (the constructor kwarg) or ``pipe`` (the axis
        name) interchangeably."""
        if plan is None or isinstance(plan, cls):
            return plan
        if isinstance(plan, dict):
            plan = dict(plan)
            if "pipe" in plan:
                plan["pipeline"] = plan.pop("pipe")
            bad = set(plan) - {"data", "model", "sequence", "pipeline"}
            if bad:
                raise ValueError("MeshPlan axes are %r, got unknown %r"
                                 % (cls.AXES, sorted(bad)))
            return cls(**plan)
        if isinstance(plan, (tuple, list)) and len(plan) in (3, 4):
            return cls(*plan)
        raise ValueError("mesh_plan must be a MeshPlan, a "
                         "{data/model/sequence/pipeline: size} dict or "
                         "a (data, model, sequence[, pipeline]) tuple, "
                         "got %r" % (plan,))

    # -- declaration ------------------------------------------------------
    def resolve(self, n_devices):
        """Fill a deferred data-axis size from the device count.  Returns
        a fully-specified plan; raises when the device pool does not
        factor."""
        ms = self.model * self.sequence * self.pipe
        if self.data is not None:
            return self
        if n_devices % ms:
            raise ValueError(
                "cannot resolve MeshPlan(model=%d, sequence=%d, "
                "pipeline=%d) over %d devices: model*sequence*pipe=%d "
                "does not divide the pool"
                % (self.model, self.sequence, self.pipe, n_devices, ms))
        return MeshPlan(data=n_devices // ms, model=self.model,
                        sequence=self.sequence, pipeline=self.pipe)

    def size(self, axis):
        v = getattr(self, axis)
        return 1 if v is None else int(v)

    @property
    def total(self):
        return (self.size("data") * self.model * self.sequence
                * self.pipe)

    def present(self, axis):
        """True when ``axis`` survives collapse (size > 1)."""
        return self.size(axis) > 1

    def axis_names(self):
        """The collapsed axis tuple (size-1 axes dropped); a fully
        degenerate plan keeps a single size-1 ``data`` axis so a mesh
        can still be built."""
        names = tuple(a for a in self.AXES if self.present(a))
        return names or ("data",)

    def axis_sizes(self):
        """Collapsed ``{axis: size}`` — feeds ``analysis.MeshSpec``."""
        return {a: self.size(a) for a in self.axis_names()}

    def axis_env(self):
        """``[(axis, size), ...]`` for ``jax.make_jaxpr(axis_env=...)``
        — the hardware-free trace of the per-replica step."""
        return [(a, self.size(a)) for a in self.axis_names()]

    def batch_axes(self):
        """The axes a (batch, tokens) batch is sharded over — what the
        gradient pmean must cover (and nothing else: DST006)."""
        return tuple(a for a in ("data", "sequence") if self.present(a))

    def batch_spec(self):
        """PartitionSpec for a rank-2 ``(batch, tokens)`` batch: batch
        dim over ``data``, token dim over ``sequence``."""
        return PartitionSpec("data" if self.present("data") else None,
                             "sequence" if self.present("sequence")
                             else None)

    # -- runtime ----------------------------------------------------------
    def build_mesh(self, devices=None):
        if devices is None:
            devices = jax.devices()
        plan = self.resolve(len(devices))
        names = plan.axis_names()
        shape = tuple(plan.size(a) for a in names)
        return make_mesh(shape, names, devices)

    def describe(self):
        return {"data": self.size("data"), "model": self.model,
                "sequence": self.sequence, "pipeline": self.pipe,
                "axes": list(self.axis_names())}

    def __repr__(self):
        return "MeshPlan(data=%r, model=%d, sequence=%d, pipeline=%d)" % (
            self.data, self.model, self.sequence, self.pipe)

    def __eq__(self, other):
        return (isinstance(other, MeshPlan) and self.data == other.data
                and self.model == other.model
                and self.sequence == other.sequence
                and self.pipe == other.pipe)


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh, axis="data"):
    """Sharding for a batch tensor: leading dim split on ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))
