"""DataParallelTrainer: one jitted SPMD program = forward + backward +
optimizer step over a device mesh.

The reference splits this across four subsystems — per-device executors
(``module/executor_group.py:143``), KVStore push/pull
(``src/kvstore/comm.h:451``), the updater loop (``python/mxnet/model.py:157``)
and the dependency engine ordering it all.  On TPU the whole iteration is a
single XLA program: batch sharded over the ``data`` mesh axis, parameters
replicated, gradients reduced by compiler-inserted psum over ICI,
parameters donated so updates happen in place in HBM.

Tensor/sequence parallelism (a ``model`` axis sharding parameters, a
``sequence`` axis sharding tokens) is NOT this replicated tier's job:
pass ``mesh_plan=``/``model_parallel=``/``sequence_parallel=`` to route
a mesh-program block (``mxnet_tpu.transformer.TransformerLM``) through
the multi-axis tier instead — docs/transformer.md.
"""
from __future__ import annotations

import collections
import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .. import autograd
from .. import engine as engine_mod
from .. import telemetry as _tele
from ..ndarray import NDArray
from ..resilience import chaos as _chaos
from . import mesh as mesh_mod
from .functional import (functionalize_forward, functional_optimizer_update,
                         accumulate_grads, tree_raw)

__all__ = ["DataParallelTrainer", "DEFAULT_CHECKPOINT_EVERY"]

# auto-checkpoint cadence when ``fit(checkpoint_dir=...)`` is given without
# an explicit ``checkpoint_every`` — the bench's ``resilience`` stage gates
# checkpoint overhead (< 5% step time) at exactly this cadence
DEFAULT_CHECKPOINT_EVERY = 50

# optimizers whose update rule is purely per-scalar (no cross-element or
# per-layer reductions), so concatenated flat buckets are numerically
# identical to per-param updates.  LBSGD (layer-wise lr from norms) and
# DCASGD (uses previous-weight deltas per layer) stay per-param.
_ELEMENTWISE_OPTIMIZERS = {
    "SGD", "NAG", "Signum", "FTML", "SGLD", "Adam", "AdaGrad", "RMSProp",
    "AdaDelta", "Ftrl", "Adamax", "Nadam",
}


class DataParallelTrainer:
    """Train a Gluon block data-parallel (optionally tensor-parallel) on a mesh.

    With ``kvstore`` set to a multi-worker ``dist_sync`` store, gradients
    are additionally averaged across processes each step (one fused
    collective over a single flat key).  Aux states (BatchNorm running
    statistics) stay per-worker, exactly like the reference's dist
    training — the kvstore moves gradients/weights only and rank 0's aux
    is what a checkpoint records (python/mxnet/model.py:157).

    Parameters
    ----------
    block : gluon.Block — the model; will be run in train mode.
    loss : gluon.loss.Loss or callable(pred, label)->NDArray.
    optimizer : str or Optimizer (same registry as the eager path).
    mesh : jax.sharding.Mesh, default = all devices on one ``data`` axis.
    param_spec_fn : callable(name, shape)->PartitionSpec overriding the
        placement of individual parameters on the data mesh; default
        replicates every parameter.  (Real tensor parallelism lives in
        the mesh tier below, not here.)
    data_axis : mesh axis name the batch is sharded over.
    mesh_plan / model_parallel / sequence_parallel : the multi-axis
        tier (docs/transformer.md): a ``MeshPlan`` over
        ``data × model × sequence`` (or per-axis sizes) training a
        mesh-program block (``mxnet_tpu.transformer.TransformerLM``)
        with Megatron-style tensor-parallel layers over ``model`` and
        ring/Ulysses attention over ``sequence``, composing with
        ``zero=1`` on the ``data`` axis.
    kvstore : str or KVStore, optional — a ``dist_sync`` store for
        multi-process gradient averaging (every process must construct
        its trainers in the same order).
    input_transform : callable(jnp array)->jnp array, optional — traced
        INSIDE the step jit and applied to the data batch first, so e.g.
        the fused uint8 pipeline tail (``mx.io.make_device_tail``) becomes
        part of the one compiled step program: XLA fuses the normalize/
        cast/layout into the first layer's prologue, the host ships raw
        uint8, and the step signature stays fixed (uint8 in — zero added
        steady-state recompiles, assertable via ``jit_cache_keys`` hooks).
    """

    # distinct flat-gradient key per trainer instance (same construction
    # order on every rank, which the collectives require anyway), so two
    # trainers on one store never collide
    _KV_UID = 0

    def __init__(self, block, loss, optimizer, optimizer_params=None,
                 mesh=None, param_spec_fn=None, data_axis="data",
                 kvstore=None, input_transform=None, run_id=None,
                 zero=0, mesh_plan=None, model_parallel=None,
                 sequence_parallel=None, dtype=None, grad_accum=1):
        from .. import kvstore as kvs
        from .. import optimizer as opt_mod
        from .. import precision as _precision
        # mixed precision (docs/precision.md): dtype="bf16" trains with
        # bf16 params/activations, f32 master weights (inside the
        # ZeRO-1 shard under zero=1), f32 gradient reduction and
        # dynamic loss scaling.  dtype=None/"float32" is the historical
        # f32 path, byte-identical to before the knob existed.
        self._dtype = _precision.resolve_dtype(dtype)
        self._reduced = _precision.is_reduced(self._dtype)
        self._block = block
        self._loss = loss
        self._input_transform = input_transform
        # multi-axis mesh tier (docs/transformer.md): a MeshPlan routes
        # a mesh-program block through the tensor/sequence-parallel
        # step instead of the replicated gluon path.  Mesh construction
        # is DEFERRED (first step / batch_sharding): the analysis path
        # (mesh_report, the tp_transformer_train_step budget model)
        # declares axis sizes and never needs devices.
        plan = mesh_mod.MeshPlan.coerce(mesh_plan)
        if plan is None and (model_parallel or sequence_parallel):
            plan = mesh_mod.MeshPlan(model=model_parallel or 1,
                                     sequence=sequence_parallel or 1)
        if plan is None and hasattr(block, "mesh_program"):
            plan = mesh_mod.MeshPlan()
        self._plan = plan
        if plan is not None:
            if not hasattr(block, "mesh_program"):
                raise ValueError(
                    "mesh_plan/model_parallel/sequence_parallel train a "
                    "mesh-program block (mxnet_tpu.transformer."
                    "TransformerLM — docs/transformer.md); %r does not "
                    "implement mesh_program()" % type(block).__name__)
            if mesh is not None:
                raise ValueError("pass either mesh= or mesh_plan=, not "
                                 "both: the plan builds its own mesh")
            if kvstore is not None:
                raise ValueError("the multi-axis mesh tier is "
                                 "single-process (in-process mesh "
                                 "collectives only); kvstore is not "
                                 "supported")
            if param_spec_fn is not None or input_transform is not None:
                raise ValueError(
                    "param_spec_fn/input_transform do not apply to the "
                    "mesh tier: the mesh program owns its own sharding "
                    "and feed (docs/transformer.md)")
        # training-run identity carried into every checkpoint's
        # provenance (ISSUE 12): the promotion audit trail names the run
        # that produced the bytes it promoted.  Deterministic by
        # construction — caller-supplied or MXTPU_RUN_ID; never a
        # timestamp (reruns must produce identical provenance).
        self.run_id = run_id if run_id is not None else \
            os.environ.get("MXTPU_RUN_ID")
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self._opt = optimizer
        # plan tier: mesh deferred to _ensure_mesh (devices may not even
        # exist on an analysis-only host)
        self._mesh = None if self._plan is not None else (
            mesh if mesh is not None else mesh_mod.data_parallel_mesh())
        self._param_spec_fn = param_spec_fn or (lambda name, shape:
                                                PartitionSpec())
        self._data_axis = data_axis
        # multi-process data parallelism (reference: dist_sync training in
        # python/mxnet/model.py:157 — grads pushed to the PS, summed across
        # workers, pulled back, updater applied locally).  Within a process
        # the mesh psum rides ICI; across processes the kvstore rides the
        # network: the two compose exactly like the reference's
        # device-comm + dist-kvstore split (src/kvstore/comm.h:451).
        if isinstance(kvstore, str):
            kvstore = kvs.create(kvstore)
        self._kv = kvstore if (kvstore is not None
                               and kvstore.num_workers > 1) else None
        if self._reduced and self._kv is not None:
            raise ValueError(
                "dtype='bf16' is not supported with a multi-process "
                "kvstore: the flat-key push/pull path reduces gradients "
                "in f32 on the PS without the loss-scale/finite "
                "bookkeeping (train bf16 in-process, or f32 with the "
                "kvstore)")
        if self._kv is not None:
            # the split-step protocol needs replace-with-sum push semantics:
            # dist_async applies pushes per-arrival on the PS (no
            # cross-worker sum) and a store-side updater would apply the
            # optimizer to the gradient keys — both would silently train
            # unsynchronized
            if self._kv.type not in ("dist_sync", "dist_device_sync",
                                     "tpu_dist"):
                raise ValueError(
                    "DataParallelTrainer needs a synchronous kvstore "
                    "(dist_sync/dist_device_sync/tpu_dist), got %r"
                    % self._kv.type)
            if getattr(self._kv, "has_updater", False):
                raise ValueError(
                    "kvstore has an updater/optimizer set; the trainer "
                    "applies its own optimizer — use a plain dist_sync "
                    "store for gradient aggregation")
            if getattr(self._kv, "compression", None) is not None:
                raise ValueError(
                    "kvstore gradient compression would quantize the "
                    "trainer's fused flat gradient (and the loss scalar "
                    "riding on it) — use an uncompressed store here")
            DataParallelTrainer._KV_UID += 1
            self._kv_prefix = "dpt%d::" % DataParallelTrainer._KV_UID
            # the kvstore already owns the cross-process reduction; the
            # mesh must therefore stay process-local or the collective
            # would be counted twice (and device_put would target
            # non-addressable devices)
            if mesh is None:
                local = jax.local_devices()
                self._mesh = mesh_mod.make_mesh(
                    (len(local),), (data_axis,), local)
            else:
                pidx = jax.process_index()
                if any(d.process_index != pidx
                       for d in self._mesh.devices.flat):
                    raise ValueError(
                        "with kvstore set the mesh must span only this "
                        "process's devices (cross-process reduction rides "
                        "the kvstore, not the mesh)")
        # ZeRO-1 (docs/elastic.md, arxiv 2004.13336): optimizer states
        # sharded over the data axis — reduce-scatter grads, shard-local
        # update, all-gather params.  The sharding rides the in-process
        # mesh; a multi-process kvstore already owns the cross-process
        # reduction and composing the two would double-count it.
        self._zero = int(zero or 0)
        if self._zero not in (0, 1):
            raise ValueError("zero must be 0 (replicated optimizer "
                             "state) or 1 (ZeRO-1 sharded), got %r"
                             % (zero,))
        if self._zero:
            if self._kv is not None:
                raise ValueError(
                    "zero=1 shards optimizer state over the mesh data "
                    "axis; combining it with a multi-process kvstore is "
                    "not supported (the kvstore path keeps the full "
                    "flat gradient per rank)")
            if type(self._opt).__name__ not in _ELEMENTWISE_OPTIMIZERS:
                raise ValueError(
                    "zero=1 updates a flat concatenated parameter shard "
                    "and therefore needs a purely elementwise optimizer "
                    "(%s); got %s"
                    % (", ".join(sorted(_ELEMENTWISE_OPTIMIZERS)),
                       type(self._opt).__name__))
        # gradient accumulation (docs/distributed.md): the step splits
        # its (per-replica) batch into ``grad_accum`` microbatches and
        # left-fold sums their gradients before the ONE optimizer
        # update — the ``parallel/functional.accumulate_grads``
        # spelling, shared with the analysis twin.  Collective count is
        # unchanged (grads reduce once, after accumulation).
        self._grad_accum = 1 if grad_accum is None else int(grad_accum)
        if self._grad_accum < 1:
            raise ValueError("grad_accum must be >= 1, got %r"
                             % (grad_accum,))
        if self._grad_accum > 1:
            if self._plan is not None:
                raise ValueError(
                    "grad_accum does not apply to the mesh tier: a "
                    "pipelined plan microbatches through the 1F1B "
                    "schedule (TransformerLMConfig(microbatches=...), "
                    "docs/pipeline.md)")
            if self._kv is not None:
                raise ValueError(
                    "grad_accum with a multi-process kvstore is not "
                    "supported: the split-step protocol pushes one "
                    "flat gradient per step")
            if self._reduced:
                raise ValueError(
                    "grad_accum with dtype='bf16' is not supported: "
                    "the loss-scale finite check is defined over one "
                    "backward pass (accumulate in f32, or use the "
                    "pipelined mesh tier for bf16 microbatching)")
        self._zero_plan = None
        self._zero_treedef = None
        self._zero_grad_fn = None
        self._zero_update_fn = None
        self._ready = False
        self._step_fn = None
        self._grad_fn = None
        self._update_fn = None
        self._step_count = 0
        # run-ahead dispatch (engine.py): every dispatched step's loss
        # handle rides this ring; waiting on it waits on the WHOLE step
        # (one program).  ``engine.bulk_size()`` bounds the ring — the
        # backpressure that keeps host run-ahead (and the HBM its queued
        # batches pin) finite.  ``engine.flush()``/``bulk()`` exit drain it.
        self._inflight = collections.deque()
        from .. import profiler as _prof
        self.dispatch_stats = _prof.PipelineStats(name="engine.dispatch")
        engine_mod.register_flusher(self.flush)

    # -- setup -------------------------------------------------------------
    @staticmethod
    def _desc_of(v):
        raw = v._data if isinstance(v, NDArray) else np.asarray(v)
        return (tuple(int(d) for d in raw.shape), str(raw.dtype))

    def _setup(self, data, label):
        block, mesh = self._block, self._mesh
        # recorded so ``restore_checkpoint`` can re-run setup from zeros
        # of the same geometry before any real batch arrives
        self._setup_desc = {"data": self._desc_of(data),
                            "label": self._desc_of(label)}
        if any(p._deferred_init
               for p in block.collect_params().values()):
            x0 = (data if isinstance(data, NDArray)
                  else NDArray(jnp.asarray(np.asarray(data))))
            x0 = x0[:1]
            if self._input_transform is not None:
                # the block only ever sees transformed batches; infer its
                # shapes from the post-tail geometry
                x0 = NDArray(self._input_transform(x0._data))
            with autograd.pause():
                block(x0)
        params = block.collect_params()
        self._params_by_name = dict(params.items())
        self._train_names = [n for n, p in params.items()
                             if p.grad_req != "null"]
        self._aux_names = [n for n, p in params.items() if p.grad_req == "null"]

        # place every param on the mesh per its PartitionSpec
        self._param_shardings = {}
        for name, p in params.items():
            spec = self._param_spec_fn(name, p.shape)
            sh = NamedSharding(mesh, spec)
            self._param_shardings[name] = sh
            p._data._set_data(jax.device_put(p.data()._data, sh))

        if self._zero:
            self._setup_zero_states()
        else:
            # group parameters into fused update buckets (reference
            # precedent: multi-tensor optimizer launches,
            # docs/faq/perf.md:214-216 "grouped updates" lever): every
            # elementwise optimizer applies the identical per-scalar
            # rule, so same-hyper same-dtype replicated params can be
            # updated as ONE flat concatenated vector — dozens of small
            # per-param fusions collapse into a handful of launches.
            import os as _os
            # opt-in: fused buckets measured ~2-4%% SLOWER end to end on
            # resnet-50/v5e even when restricted to tiny BN/bias params
            # — the concat barriers the backward->optimizer overlap that
            # XLA otherwise schedules per-gradient
            # (docs/perf_resnet50_tpu.md "levers measured and
            # rejected").  Kept env-gated for workloads with thousands
            # of small params.  The FUSED Pallas update (docs/fusion.md)
            # rides the same bucket machinery: one flat f32 space, one
            # kernel pass — on by default on TPU for SGD/Adam, forced
            # elsewhere via MXTPU_FUSED_OPTIMIZER=1.
            from ..ops import fused_optimizer as _fused
            fused_on = (_fused.fused_update_enabled()
                        and _fused.supports(self._opt) is not None)
            groupable = type(self._opt).__name__ in \
                _ELEMENTWISE_OPTIMIZERS \
                and (_os.environ.get("MXTPU_GROUP_UPDATES", "0") == "1"
                     or fused_on)
            max_group_elems = int(_os.environ.get(
                "MXTPU_GROUP_MAX_ELEMS",
                str((1 << 62) if fused_on else 65536)))
            buckets = {}
            self._groups = []  # list of [name, ...]
            for name in self._train_names:
                p = self._params_by_name[name]
                spec = self._param_spec_fn(name, p.shape)
                psize = 1
                for d in p.shape:
                    psize *= int(d)
                if not groupable or spec != PartitionSpec() or \
                        psize > max_group_elems:
                    self._groups.append([name])
                    continue
                key = (float(p.lr_mult), float(p.wd_mult),
                       str(np.dtype(p.dtype) if p.dtype else "float32"))
                buckets.setdefault(key, []).append(name)
            self._groups = [v for v in buckets.values()] + self._groups

            # optimizer states live next to their (possibly sharded)
            # params; grouped buckets get one state over the flat concat
            self._states_raw = []
            self._group_shardings = []
            for gi, names in enumerate(self._groups):
                ps = [self._params_by_name[n] for n in names]
                if len(names) == 1:
                    wflat = ps[0].data()._data
                    sh = self._param_shardings[names[0]]
                else:
                    wflat = jnp.concatenate([p.data()._data.ravel()
                                             for p in ps])
                    sh = NamedSharding(mesh, PartitionSpec())
                self._group_shardings.append(sh)
                state = self._opt.create_state_multi_precision(
                    gi, NDArray(wflat))
                raw = tree_raw(state)
                self._states_raw.append(jax.tree_util.tree_map(
                    lambda v: jax.device_put(v, sh), raw))
                if ps[0].lr_mult != 1.0:
                    self._opt.lr_mult.setdefault(gi, ps[0].lr_mult)
                if ps[0].wd_mult != 1.0:
                    self._opt.wd_mult.setdefault(gi, ps[0].wd_mult)

        def run(x, y):
            if self._input_transform is not None:
                # traced here, inside the step jit: the pipeline tail
                # (normalize/cast/layout) fuses into the step program and
                # the program's input signature stays the host's narrow
                # uint8 batch
                x = NDArray(self._input_transform(x._data))
            out = block(x)
            l = self._loss(out, y)
            return l.mean() if hasattr(l, "mean") else l

        self._fwd = functionalize_forward(
            run, self._params_by_name, self._train_names, self._aux_names,
            train=True)

        # dist: ONE flat key holds every gradient plus the loss scalar, so
        # each step is a single cross-worker collective instead of one per
        # parameter (no server-side updater: each sync round replaces the
        # value with the sum of that round's pushes, which is exactly
        # gradient aggregation)
        if self._kv is not None:
            sizes = []
            for name in self._train_names:
                p = self._params_by_name[name]
                n = 1
                for d in p.shape:
                    n *= int(d)
                sizes.append(n)
            self._flat_sizes = sizes
            self._flat_key = self._kv_prefix + "flat"
            total = sum(sizes) + 1  # +1: the loss scalar rides along
            self._kv.init(self._flat_key, NDArray(jnp.zeros((total,),
                                                            jnp.float32)))
            self._flat_out = NDArray(jnp.zeros((total,), jnp.float32))
            self._validate_flat_key(total)
        if self._reduced:
            self._init_loss_scale_state()
        self._ready = True

    def _init_loss_scale_state(self):
        """Device-resident loss-scale machine state (docs/precision.md):
        scale, consecutive-good-step counter, skipped-step total.  Held
        as lazy device scalars so the step never syncs; ``flush()``
        publishes them through the telemetry registry."""
        from .. import precision as _precision
        self._ls_scale, self._ls_good = _precision.init_loss_scale()
        self._ls_skipped = jnp.zeros((), jnp.int32)
        self._ls_reported_skipped = 0

    def _validate_flat_key(self, total):
        """Detect cross-rank trainer desync before any gradient mixes.

        The flat-key scheme assumes identical trainer construction order
        on every rank; two equal-length flat keys from *different*
        trainers would otherwise sum silently (the cross-process
        collective is unkeyed).  One signature round catches it: every
        rank pushes a layout fingerprint in slot 0; the pulled sum must be
        num_workers * sig (sig < 2^16 keeps k*sig inside fp32's 24
        significand bits for k <= 256 workers, so healthy sums compare
        exactly; a desync shifts the sum by ~|sigA - sigB| >> 1)."""
        if self._kv.num_workers <= 1:
            return
        import zlib
        sig = float(zlib.crc32(repr(
            (self._flat_key, tuple(self._flat_sizes))).encode())
            % (1 << 16) + 1)
        probe = jnp.zeros((total,), jnp.float32).at[0].set(sig)
        self._kv.push(self._flat_key, NDArray(probe))
        out = NDArray(jnp.zeros((total,), jnp.float32))
        self._kv.pull(self._flat_key, out=out)
        got = float(out.asnumpy()[0])
        want = sig * self._kv.num_workers
        # tolerant compare: beyond 256 workers the fp32 partial sums may
        # round by a few ulps; any real desync moves the sum by >= ~1
        if abs(got - want) > 0.5:
            raise RuntimeError(
                "DataParallelTrainer flat-key desync: rank %d pushed "
                "signature %.0f for key %r sizes %r but the cross-worker "
                "sum was %.0f (expected %.0f) — trainers were constructed "
                "in a different order on some rank, which would silently "
                "sum gradients from different models"
                % (self._kv.rank, sig, self._flat_key,
                   tuple(self._flat_sizes), got, want))

    # -- ZeRO-1 sharded optimizer runtime (parallel/zero.py) ---------------
    @property
    def zero(self):
        return self._zero

    def _zero_axis_size(self):
        sizes = dict(zip(self._mesh.axis_names, self._mesh.devices.shape))
        return int(sizes.get(self._data_axis, 1))

    def _zero_param_dtypes(self):
        """Per-param dtype strings for the flat plan.  Mixed precision
        runs the LIVE params (the all_gather reassembly targets) in the
        compute dtype; the f32 masters live outside the plan, as the
        explicit ``(shard,)`` master argument."""
        if self._reduced:
            return [str(jnp.dtype(self._dtype))] * len(self._train_names)
        return [str(np.dtype(self._params_by_name[n].dtype or "float32"))
                for n in self._train_names]

    def _setup_zero_states(self):
        """Build the flat ZeRO-1 plan and the sharded optimizer state:
        one ``(padded,)`` f32 array per state leaf, ``P(data)``-sharded
        over the mesh so each device physically holds its 1/K shard."""
        from . import zero as _zero
        mesh = self._mesh
        for name in self._train_names:
            p = self._params_by_name[name]
            if self._param_spec_fn(name, p.shape) != PartitionSpec():
                raise ValueError(
                    "zero=1 flattens the trainable parameters over the "
                    "data axis and needs them replicated; param %r has "
                    "a non-trivial PartitionSpec" % (name,))
            if p.lr_mult != 1.0 or p.wd_mult != 1.0:
                raise ValueError(
                    "zero=1 applies one flat optimizer update and "
                    "cannot honor per-parameter lr_mult/wd_mult "
                    "(param %r)" % (name,))
        k = self._zero_axis_size()
        plan = _zero.Zero1Plan(
            self._train_names,
            [self._params_by_name[n].shape for n in self._train_names],
            self._zero_param_dtypes(),
            self._data_axis, k)
        self._zero_plan = plan
        state_sh = NamedSharding(mesh, PartitionSpec(self._data_axis))
        if self._reduced:
            # f32 MASTER weights, stored ONLY as the P(data)-sharded
            # flat vector (arxiv 2004.13336's layout): seeded from the
            # still-f32 initial params, then the live bf16 params are
            # cast FROM them.  After this point no unsharded f32 copy
            # of the weights exists anywhere (docs/precision.md;
            # addressable_shards-asserted in tests/test_precision.py).
            from . import zero as _zmod
            master = _zmod._flatten_pad(
                [self._params_by_name[n].data()._data
                 for n in self._train_names], plan, jnp)
            self._zero_master = jax.device_put(master, state_sh)
            for n in self._train_names:
                p = self._params_by_name[n]
                p._data._set_data(jax.device_put(
                    p.data()._data.astype(self._dtype),
                    self._param_shardings[n]))
        else:
            self._zero_master = None
        flat_w = jnp.zeros((plan.padded,), jnp.float32)
        state = self._opt.create_state_multi_precision(0, NDArray(flat_w))
        raw = tree_raw(state)
        leaves, treedef = jax.tree_util.tree_flatten(raw)
        for li, leaf in enumerate(leaves):
            shape = tuple(getattr(leaf, "shape", ()))
            if shape != (plan.padded,):
                raise ValueError(
                    "zero=1 needs every optimizer-state leaf shaped "
                    "like the flat weight vector; leaf %d of %s has "
                    "shape %r (flat is (%d,))"
                    % (li, type(self._opt).__name__, shape, plan.padded))
        self._zero_treedef = treedef
        raw = jax.tree_util.tree_map(
            lambda v: jax.device_put(v, state_sh), raw)
        self._groups = [list(self._train_names)]
        self._group_shardings = [state_sh]
        self._states_raw = [raw]

    def _zero_leaves(self):
        return tuple(jax.tree_util.tree_leaves(self._states_raw[0]))

    def _zero_step(self, train_vals, aux_vals, x, y, rng, lr_host,
                   tele_on, attr, t1):
        """ZeRO-1 split step: local grads + reduce-scatter (one jitted
        shard_map program), then shard-local update + all-gather (a
        second one).  The split mirrors ``_dist_step``'s grad→exchange→
        update shape: the collective program's host time bills to the
        ``collective_or_ps`` attribution phase, so the doctor sees the
        reduce-scatter/all-gather shift under zero=1."""
        from . import zero as _zero
        if self._zero_grad_fn is None:
            self._zero_grad_fn, self._zero_update_fn = \
                _zero.build_runtime_fns(
                    self._fwd, self._opt, self._zero_plan,
                    self._zero_treedef, self._mesh,
                    compute_dtype=self._dtype if self._reduced else None,
                    grad_accum=self._grad_accum)
            if tele_on:
                attr.set_context("collective_or_ps", "zero1")
                if self._grad_accum > 1:
                    attr.set_context("dispatch", "grad_accum")
        if self._reduced:
            g_sh, loss_val, muts, fin = self._zero_grad_fn(
                train_vals, aux_vals, x, y, rng, self._ls_scale)
            if tele_on:
                t2 = time.perf_counter()
                attr.add_phase("dispatch", t2 - t1)
            (new_vals, new_master, new_leaves, new_scale, new_good,
             new_skipped) = self._zero_update_fn(
                train_vals, self._zero_master, self._zero_leaves(),
                g_sh, jnp.float32(lr_host), jnp.int32(self._step_count),
                self._ls_scale, self._ls_good, self._ls_skipped, fin)
            self._zero_master = new_master
            self._ls_scale, self._ls_good = new_scale, new_good
            self._ls_skipped = new_skipped
        else:
            g_sh, loss_val, muts = self._zero_grad_fn(
                train_vals, aux_vals, x, y, rng)
            if tele_on:
                t2 = time.perf_counter()
                attr.add_phase("dispatch", t2 - t1)
            new_vals, new_leaves = self._zero_update_fn(
                train_vals, self._zero_leaves(), g_sh,
                jnp.float32(lr_host), jnp.int32(self._step_count))
        self._states_raw = [jax.tree_util.tree_unflatten(
            self._zero_treedef, list(new_leaves))]
        if tele_on:
            attr.add_phase("collective_or_ps",
                           time.perf_counter() - t2)
        return loss_val, new_vals, muts

    def _build_zero_replica_step(self, declared_k=None):
        """The per-replica spelling of the zero=1 step at a *declared*
        axis size (no devices needed): the analysis twin of the runtime
        shard_map programs, built from the same ``parallel/zero.py``
        parts so the executed and analyzed programs cannot drift.
        Returns ``(step_fn, plan)``."""
        from . import zero as _zero
        k = int(declared_k or self._zero_axis_size())
        plan = _zero.Zero1Plan(
            self._train_names,
            [self._params_by_name[n].shape for n in self._train_names],
            self._zero_param_dtypes(),
            self._data_axis, k)
        return _zero.build_replica_step(
            self._fwd, self._opt, plan, self._zero_treedef,
            compute_dtype=self._dtype if self._reduced else None,
            grad_accum=self._grad_accum), plan

    def zero_report(self, data_shape=None, label_shape=None,
                    data_dtype="float32", label_dtype="int32",
                    declared_axis_size=None):
        """Static proof bundle of the zero=1 runtime step:
        ``(CostReport, [Finding], ShardReport)`` over the REAL runtime
        spelling traced at a declared axis size — the mxcost tape
        (peak HBM with params/states donated, batch host-fed), the
        mixed-axis DST lint (a deleted runtime all-gather is DST007)
        and the priced reduce-scatter/all-gather schedule.  Hardware-
        free; what ``analysis/budget_models.zero1_mlp_train_step``
        gates against ``STATIC_BUDGETS.json``."""
        import numpy as _onp

        from ..analysis import cost as _cost
        from ..analysis import shard_prop as _sp

        if not self._zero:
            raise ValueError("zero_report needs a zero=1 trainer")
        if not self._ready:
            if data_shape is None:
                raise ValueError(
                    "trainer has not stepped yet: pass data_shape (and "
                    "label_shape)")
            x0 = NDArray(jnp.zeros(tuple(data_shape),
                                   _onp.dtype(data_dtype)))
            y0 = NDArray(jnp.zeros(
                tuple(label_shape or (data_shape[0],)),
                _onp.dtype(label_dtype)))
            self._setup(x0, y0)
        data_shape = tuple(data_shape)
        label_shape = tuple(label_shape or (data_shape[0],))
        k = int(declared_axis_size or self._zero_axis_size())
        step, plan = self._build_zero_replica_step(k)
        shard_local = max(data_shape[0] // max(k, 1), 1)
        dtypes = self._zero_param_dtypes()
        train_avals = tuple(
            jax.ShapeDtypeStruct(
                tuple(self._params_by_name[n].shape), _onp.dtype(dt))
            for n, dt in zip(self._train_names, dtypes))
        n_leaves = len(self._zero_leaves())
        state_avals = tuple(
            jax.ShapeDtypeStruct((plan.shard,), _onp.float32)
            for _ in range(n_leaves))
        aux_avals = tuple(
            jax.ShapeDtypeStruct(
                tuple(self._params_by_name[n].shape),
                _onp.dtype(self._params_by_name[n].dtype or "float32"))
            for n in self._aux_names)
        xs = jax.ShapeDtypeStruct((shard_local,) + data_shape[1:],
                                  _onp.dtype(data_dtype))
        ys = jax.ShapeDtypeStruct((shard_local,) + label_shape[1:],
                                  _onp.dtype(label_dtype))
        key = jax.ShapeDtypeStruct((2,), _onp.uint32)
        n_train = len(train_avals)
        if self._reduced:
            # reduced spelling adds the (shard,) f32 master invar after
            # the params and the three loss-scale scalars at the tail
            master_aval = jax.ShapeDtypeStruct((plan.shard,),
                                               _onp.float32)
            closed = jax.make_jaxpr(
                step, axis_env=[(self._data_axis, k)])(
                train_avals, master_aval, state_avals, aux_avals,
                xs, ys, key, jnp.float32(0.01), jnp.int32(1),
                jnp.float32(2.0 ** 15), jnp.int32(0), jnp.int32(0))
            n_sharded = n_train + 1 + n_leaves
            host = [n_sharded + len(aux_avals),
                    n_sharded + len(aux_avals) + 1]
            shard_dims = {n_train: {0: (self._data_axis,)}}
            shard_dims.update({n_train + 1 + li: {0: (self._data_axis,)}
                               for li in range(n_leaves)})
        else:
            closed = jax.make_jaxpr(
                step, axis_env=[(self._data_axis, k)])(
                train_avals, state_avals, aux_avals, xs, ys, key,
                jnp.float32(0.01), jnp.int32(1))
            n_sharded = n_train + n_leaves
            host = [n_sharded + len(aux_avals),
                    n_sharded + len(aux_avals) + 1]
            shard_dims = {n_train + li: {0: (self._data_axis,)}
                          for li in range(n_leaves)}
        donated = list(range(n_sharded))
        report = _cost.analyze_jaxpr(
            closed, axis_sizes={self._data_axis: k},
            donated_invars=donated, host_invars=host)
        report.transfer_d2h_bytes = 4    # only the loss comes back
        mesh = _sp.MeshSpec({self._data_axis: k})
        findings = _sp.lint_sharded_step(
            closed, mesh, data_axes=(self._data_axis,),
            varying_invars=host,
            shard_dims=shard_dims,
            param_outvars=list(range(1, 1 + n_train)),
            param_names=list(self._train_names),
            subject="DataParallelTrainer(zero=1)")
        findings += _cost.unpriced_findings(
            report, subject="DataParallelTrainer(zero=1)")
        shard = _sp.collective_schedule(
            closed, mesh, subject="DataParallelTrainer(zero=1)")
        shard.extras.update({
            "zero1_plan": plan.describe(),
            "runtime_peak_hbm_bytes": int(report.peak_hbm_bytes),
        })
        # traced program + axis sizes for fusion_report (private: the
        # fusion pass re-walks the same tape the cost pass priced)
        shard._fusion_ctx = (closed, {self._data_axis: k})
        return report, findings, shard

    # -- multi-axis mesh tier (mxnet_tpu.transformer) ----------------------
    @property
    def mesh_plan(self):
        return self._plan

    def _ensure_mesh(self):
        """Resolve the plan against the live device pool and build the
        collapsed mesh (deferred from __init__ so analysis-only hosts
        never need the devices)."""
        if self._mesh is not None:
            return
        self._plan = self._plan.resolve(len(jax.devices()))
        self._mesh = self._plan.build_mesh()

    def _mesh_apply_update(self, treedefs):
        """The gluon optimizer as the mesh step's shard-local update:
        the SAME ``Optimizer.update`` numerics as every other tier,
        traced through ``functional_optimizer_update`` over the local
        shard (elementwise rules are shard-invariant)."""
        opt = self._opt

        def apply_update(i, w, g, leaves, lr, t):
            state = jax.tree_util.tree_unflatten(treedefs[i],
                                                 list(leaves))
            nw, ns = functional_optimizer_update(opt, i, w, g, state,
                                                 lr, t)
            return nw, tuple(jax.tree_util.tree_leaves(ns))

        return apply_update

    def _setup_mesh(self, data, label):
        """Materialize the mesh tier: params placed per the program's
        PartitionSpecs, optimizer state per-param (or ZeRO-1 flat over
        ``model × data`` under ``zero=1``), the two jitted ``shard_map``
        programs built from the ONE per-replica spelling
        (``transformer/step.py``)."""
        from ..transformer import step as _tstep
        self._ensure_mesh()
        plan, mesh = self._plan, self._mesh
        program = self._block.mesh_program(plan)
        self._mesh_program = program
        self._setup_desc = {"data": self._desc_of(data),
                            "label": self._desc_of(label)}
        dshape = self._setup_desc["data"][0]
        if len(dshape) != 2 or dshape[1] != program.cfg.seq_len:
            raise ValueError(
                "mesh-tier batches are (batch, tokens) int32 with "
                "tokens == cfg.seq_len (%d); got shape %r"
                % (program.cfg.seq_len, tuple(dshape)))
        if dshape[0] % plan.size("data"):
            raise ValueError(
                "global batch %d must divide by the data axis %d "
                "(plan %r)" % (dshape[0], plan.size("data"), plan))
        if program.pipelined:
            b_local = dshape[0] // plan.size("data")
            if b_local % program.n_micro:
                raise ValueError(
                    "pipeline=%d runs %d microbatches: the per-replica "
                    "batch %d must divide by them (global batch %d, "
                    "data axis %d)"
                    % (plan.size("pipe"), program.n_micro, b_local,
                       dshape[0], plan.size("data")))
        params = program.init_params()
        self._mesh_param_names = list(program.param_names)
        self._mesh_params = {
            name: jax.device_put(
                params[name],
                NamedSharding(mesh, program.partition_spec(name)))
            for name in self._mesh_param_names}

        from jax.sharding import PartitionSpec as P
        if self._zero:
            zp = _tstep.TPZeroPlan(program, plan.size("data"))
            self._mesh_zero_plan = zp
            template = self._opt.create_state_multi_precision(
                0, NDArray(jnp.zeros((zp.shard,), jnp.float32)))
            raw = tree_raw(template)
            leaves, treedef = jax.tree_util.tree_flatten(raw)
            for li, leaf in enumerate(leaves):
                if tuple(getattr(leaf, "shape", ())) != (zp.shard,):
                    raise ValueError(
                        "zero=1 needs flat-shaped optimizer state "
                        "leaves; leaf %d of %s has shape %r"
                        % (li, type(self._opt).__name__,
                           tuple(getattr(leaf, "shape", ()))))
            self._mesh_state_treedefs = [treedef]
            flat_axes = tuple(a for a in ("pipe", "model", "data")
                              if plan.present(a))
            spec = P(flat_axes) if flat_axes else P()
            self._mesh_state_specs = [spec] * len(leaves)
            kpm = plan.size("pipe") * plan.size("model")
            self._mesh_state_leaves = tuple(
                jax.device_put(jnp.zeros((kpm * zp.padded,), jnp.float32),
                               NamedSharding(mesh, spec))
                for _ in leaves)
            self._mesh_leaf_counts = None
        else:
            self._mesh_zero_plan = None
            treedefs, leaf_counts, state_leaves, state_specs = \
                [], [], [], []
            for i, name in enumerate(self._mesh_param_names):
                w = self._mesh_params[name]
                state = self._opt.create_state_multi_precision(
                    i, NDArray(jnp.asarray(params[name])))
                raw = tree_raw(state)
                leaves, treedef = jax.tree_util.tree_flatten(raw)
                treedefs.append(treedef)
                leaf_counts.append(len(leaves))
                spec = program.partition_spec(name)
                for leaf in leaves:
                    state_leaves.append(jax.device_put(
                        jnp.asarray(leaf), NamedSharding(mesh, spec)))
                    state_specs.append(spec)
            self._mesh_state_treedefs = treedefs
            self._mesh_leaf_counts = leaf_counts
            self._mesh_state_specs = state_specs
            self._mesh_state_leaves = tuple(state_leaves)

        apply_update = self._mesh_apply_update(self._mesh_state_treedefs)
        self._mesh_grad_fn, self._mesh_update_fn = \
            _tstep.build_runtime_fns(
                program, apply_update, self._mesh_leaf_counts, mesh,
                self._mesh_state_specs, zero=self._zero,
                zero_plan=self._mesh_zero_plan,
                compute_dtype=self._dtype if self._reduced else None)
        if _tele._ENABLED:
            _tele.attribution().set_context("collective_or_ps",
                                            self._mesh_context_tag())
        self._ready = True

    def _mesh_context_tag(self):
        """Which mesh axis the doctor should name when collective time
        dominates: the axis carrying more MODELED wire bytes in the
        step's priced schedule (docs/transformer.md; the CONTEXT_HINTS
        entries in telemetry/attribution.py)."""
        plan = self._plan
        tags = {"model": "tp_model", "sequence": "tp_sequence",
                "pipe": "pp_pipeline"}
        armed = [a for a in ("model", "sequence", "pipe")
                 if plan.present(a)]
        if len(armed) == 1:
            return tags[armed[0]]
        try:
            desc = self._setup_desc["data"][0]
            _, _, shard = self.mesh_report(
                data_shape=tuple(desc), declared_plan=plan)
            per_axis = shard.collective_bytes_per_axis
            best = max(armed or ["model"],
                       key=lambda a: per_axis.get(a, 0))
            return tags[best]
        except Exception:
            return "tp_model"

    def _step_mesh_tier(self, data, label):
        """One mesh-tier training step (the ``step()`` route when a
        MeshPlan is armed): same chaos probe, attribution phases and
        run-ahead bookkeeping as the replicated step — grad program
        bills ``dispatch``, update program (the ZeRO rs/ag under
        ``zero=1``) bills ``collective_or_ps``."""
        from .. import _rng
        if not self._ready:
            self._setup_mesh(data, label)
        tele_on = _tele._ENABLED
        attr = _tele.attribution() if tele_on else None
        if tele_on:
            attr.on_step(self._step_count + 1)
        batch_sh = self.batch_sharding
        t0 = time.perf_counter() if tele_on else 0.0
        x = self._put_batch(data, batch_sh)
        y = self._put_batch(label, batch_sh)
        if tele_on:
            t1 = time.perf_counter()
            attr.add_phase("h2d_transfer", t1 - t0)
        else:
            t1 = 0.0
        self._step_count += 1
        _chaos.maybe_inject("trainer.step", self._step_count, ctx=self)
        self._opt.num_update = self._step_count
        lr_host = (self._opt.lr_scheduler(self._step_count)
                   if self._opt.lr_scheduler else self._opt.lr)
        train_vals = tuple(self._mesh_params[n]
                           for n in self._mesh_param_names)
        rng = _rng.next_key()
        grads, loss_val = self._mesh_grad_fn(train_vals, x, y, rng)
        if tele_on:
            t2 = time.perf_counter()
            attr.add_phase("dispatch", t2 - t1)
        new_vals, new_leaves = self._mesh_update_fn(
            train_vals, self._mesh_state_leaves, grads,
            jnp.float32(lr_host), jnp.int32(self._step_count))
        if tele_on:
            attr.add_phase("collective_or_ps",
                           time.perf_counter() - t2)
        for name, val in zip(self._mesh_param_names, new_vals):
            self._mesh_params[name] = val
        self._mesh_state_leaves = tuple(new_leaves)
        self._track_inflight(loss_val)
        return NDArray(loss_val)

    def mesh_report(self, data_shape=None, label_shape=None,
                    declared_plan=None):
        """Static proof bundle of the multi-axis step:
        ``(CostReport, [Finding], ShardReport)`` over the REAL runtime
        spelling traced at the plan's declared axis sizes — hardware
        free.  The ShardReport prices the mixed-axis collective
        schedule (``collective_bytes_per_axis`` splits ``model`` vs
        ``sequence`` wire bytes); the findings run the mixed-axis DST
        rules (a deleted row-parallel psum surfaces as a pending
        partial-sum DST001 per parameter) and, under ring attention,
        the DST009 ring proof over ``sequence``.  What the
        ``tp_transformer_train_step`` budget model gates against
        ``STATIC_BUDGETS.json``."""
        import numpy as _onp

        from ..analysis import cost as _cost
        from ..analysis import shard_prop as _sp
        from ..transformer import step as _tstep
        from . import pipeline as _pp

        if self._plan is None:
            raise ValueError("mesh_report needs a mesh_plan trainer")
        plan = mesh_mod.MeshPlan.coerce(declared_plan) or self._plan
        if plan.data is None:
            raise ValueError(
                "mesh_report needs fully-declared axis sizes: pass "
                "declared_plan=MeshPlan(data=K, ...) (the runtime plan "
                "has a deferred data axis)")
        program = self._block.mesh_program(plan)
        if data_shape is None:
            data_shape = (8 * plan.size("data"),
                          program.cfg.seq_len)
        b_local, t_local = program.local_batch_shape(int(data_shape[0]))

        # optimizer-state leaf structure from a host-side template
        if self._zero:
            zp = _tstep.TPZeroPlan(program, plan.size("data"))
            template = self._opt.create_state_multi_precision(
                0, NDArray(jnp.zeros((zp.shard,), jnp.float32)))
            leaves, treedef = jax.tree_util.tree_flatten(
                tree_raw(template))
            treedefs, leaf_counts = [treedef], None
            state_avals = tuple(
                jax.ShapeDtypeStruct((zp.shard,), _onp.float32)
                for _ in leaves)
            flat_axes = tuple(a for a in ("pipe", "model", "data")
                              if plan.present(a))
            state_dims = {0: flat_axes} if flat_axes else {}
            state_shard_dims = [state_dims] * len(leaves)
        else:
            zp = None
            treedefs, leaf_counts = [], []
            state_avals, state_shard_dims = [], []
            for i, name in enumerate(program.param_names):
                lshape = program.local_shape(name)
                template = self._opt.create_state_multi_precision(
                    i, NDArray(jnp.zeros(lshape, jnp.float32)))
                leaves, treedef = jax.tree_util.tree_flatten(
                    tree_raw(template))
                treedefs.append(treedef)
                leaf_counts.append(len(leaves))
                spec = program.partition_spec(name)
                dims = {d: (e,) for d, e in enumerate(spec)
                        if e is not None}
                for leaf in leaves:
                    state_avals.append(jax.ShapeDtypeStruct(
                        tuple(leaf.shape), _onp.float32))
                    state_shard_dims.append(dims)
            state_avals = tuple(state_avals)

        step = _tstep.build_replica_step(
            program, self._mesh_apply_update(treedefs), leaf_counts,
            zero=self._zero, zero_plan=zp,
            compute_dtype=self._dtype if self._reduced else None)
        train_avals = tuple(
            jax.ShapeDtypeStruct(program.local_shape(n), _onp.float32)
            for n in program.param_names)
        xs = jax.ShapeDtypeStruct((b_local, t_local), _onp.int32)
        ys = jax.ShapeDtypeStruct((b_local, t_local), _onp.int32)
        key = jax.ShapeDtypeStruct((2,), _onp.uint32)
        closed = jax.make_jaxpr(step, axis_env=plan.axis_env())(
            train_avals, state_avals, xs, ys, key,
            jnp.float32(0.01), jnp.int32(1))

        n_train = len(train_avals)
        n_state = len(state_avals)
        host = [n_train + n_state, n_train + n_state + 1]
        report = _cost.analyze_jaxpr(
            closed, axis_sizes=plan.axis_sizes(),
            donated_invars=list(range(n_train + n_state)),
            host_invars=host)
        report.transfer_d2h_bytes = 4    # only the loss comes back

        mesh_spec = _sp.MeshSpec(plan.axis_sizes())
        shard_dims = {}
        for i, name in enumerate(program.param_names):
            spec = program.partition_spec(name)
            dims = {d: (e,) for d, e in enumerate(spec)
                    if e is not None}
            if dims:
                shard_dims[i] = dims
        for li, dims in enumerate(state_shard_dims):
            if dims:
                shard_dims[n_train + li] = dims
        findings = _sp.lint_sharded_step(
            closed, mesh_spec, data_axes=plan.batch_axes(),
            varying_invars=host, shard_dims=shard_dims,
            param_outvars=list(range(1, 1 + n_train)),
            param_names=list(program.param_names),
            subject="DataParallelTrainer(mesh_plan=%s)"
                    % (plan.describe()["axes"],))
        if plan.present("sequence") and \
                program.attention_mode == "ring":
            # under pipeline=K the block (and its attention ring) runs
            # inside the tick scan: one full ring per tick
            ring_outer = (_pp.pipeline_ticks(plan.size("pipe"),
                                             program.n_micro)
                          if program.pipelined else 1)
            findings += _sp.lint_ring_schedule(
                closed, "sequence", plan.size("sequence"),
                subject="DataParallelTrainer.mesh ring attention",
                outer_scale=ring_outer)
        if program.pipelined:
            act_itemsize = 2 if self._reduced else 4
            stash_bytes = (b_local * t_local
                           * program.cfg.d_model * act_itemsize)
            pipe_sharded = [
                i for i, name in enumerate(program.param_names)
                if "pipe" in {e for e in program.partition_spec(name)
                              if e is not None}]
            findings += _sp.lint_pipeline_step(
                closed, plan.axis_sizes(), program.n_micro,
                stash_bytes=stash_bytes,
                peak_hbm_bytes=report.peak_hbm_bytes,
                # the ZeRO-1 flat concat mixes every param into one
                # vector — the taint half only proves the per-param
                # spelling (lint_pipeline_step docstring)
                param_outvars=([] if self._zero
                               else list(range(1, 1 + n_train))),
                param_names=list(program.param_names),
                pipe_sharded=pipe_sharded,
                subject="DataParallelTrainer(mesh_plan pipeline)")
        findings += _cost.unpriced_findings(
            report, subject="DataParallelTrainer(mesh_plan)")
        shard = _sp.collective_schedule(
            closed, mesh_spec,
            subject="DataParallelTrainer(mesh_plan)")
        per_axis = shard.collective_bytes_per_axis
        shard.extras.update({
            "plan": plan.describe(),
            "program": program.describe(),
            "attention_mode": program.attention_mode,
            "tp_modeled_model_axis_bytes": int(per_axis.get("model", 0)),
            "tp_modeled_sequence_axis_bytes": int(
                per_axis.get("sequence", 0)),
            "runtime_peak_hbm_bytes": int(report.peak_hbm_bytes),
        })
        if program.pipelined:
            kp, m = plan.size("pipe"), program.n_micro
            ticks = _pp.pipeline_ticks(kp, m)
            act_itemsize = 2 if self._reduced else 4
            hop = ((b_local // m) * t_local * program.cfg.d_model
                   * act_itemsize)
            shard.extras.update({
                "pp_modeled_pipe_axis_bytes": int(
                    per_axis.get("pipe", 0)),
                "pp_modeled_bubble_frac": _pp.bubble_fraction(kp, m),
                "pp_microbatches": int(m),
                "pp_ticks": int(ticks),
                "pp_hop_bytes": int(hop),
                "pp_stash_bytes": int(b_local * t_local
                                      * program.cfg.d_model
                                      * act_itemsize),
            })
        if zp is not None:
            shard.extras["tp_zero1_plan"] = zp.describe()
        # traced program + axis sizes for fusion_report (private)
        shard._fusion_ctx = (closed, dict(plan.axis_sizes()))
        return report, findings, shard

    def mesh_params(self):
        """The trained GLOBAL parameter arrays, name -> float32 ndarray
        in ``MeshProgram.param_names`` order — exactly the layout
        ``init_params`` produces and the serving tier's ``DecodeRunner``
        consumes.  Sharded device values gather to their global shape
        here; only meaningful on the mesh tier after the first step."""
        if getattr(self, "_mesh_params", None) is None:
            raise RuntimeError(
                "mesh_params() needs the mesh tier set up (train at "
                "least one step with mesh_plan=...)")
        return {name: np.asarray(self._mesh_params[name])
                for name in self._mesh_param_names}

    # -- mesh-tier checkpointing -------------------------------------------
    def _save_mesh(self, directory, epoch=None, nbatch=None, keep=3):
        """Monolithic snapshot of the mesh tier (program param names are
        deterministic — no gensym mapping needed; states are the flat
        global leaves, fleet-size-free because the mesh is in-process)."""
        from .. import _rng
        from ..resilience import checkpoint as _ckpt
        payload = {
            "mesh_params": {
                name: _ckpt.encode_array(self._mesh_params[name])
                for name in self._mesh_param_names},
            "mesh_states": [_ckpt.encode_array(v)
                            for v in self._mesh_state_leaves],
            "step_count": self._step_count,
            "rng": _rng.get_state(),
            "numpy_global": np.random.get_state(),
            "cursor": {"epoch": epoch, "nbatch": nbatch},
            "setup_desc": self._setup_desc,
            "plan": self._plan.describe(),
            "program": self._mesh_program.describe(),
        }
        return _ckpt.save_checkpoint(
            directory, payload, self._step_count, keep=keep,
            provenance={"epoch": epoch, "train_run_id": self.run_id,
                        "digest": _ckpt.payload_digest(payload)})

    def _restore_mesh(self, rec):
        from .. import _rng
        from ..resilience import checkpoint as _ckpt
        payload = rec["payload"]
        if "mesh_params" not in payload:
            raise RuntimeError(
                "checkpoint is not a mesh-tier snapshot (trained by a "
                "different trainer tier?)")
        if not self._ready:
            dshape, ddt = payload["setup_desc"]["data"]
            lshape, ldt = payload["setup_desc"]["label"]
            self._setup_mesh(NDArray(jnp.zeros(dshape, np.dtype(ddt))),
                             NDArray(jnp.zeros(lshape, np.dtype(ldt))))
        if payload["program"] != self._mesh_program.describe():
            raise RuntimeError(
                "checkpoint program %r does not match this trainer's "
                "%r (different config/plan)"
                % (payload["program"], self._mesh_program.describe()))
        mesh = self._mesh
        for name in self._mesh_param_names:
            self._mesh_params[name] = jax.device_put(
                jnp.asarray(_ckpt.decode_array(
                    payload["mesh_params"][name])),
                NamedSharding(mesh,
                              self._mesh_program.partition_spec(name)))
        encs = payload["mesh_states"]
        if len(encs) != len(self._mesh_state_leaves):
            raise RuntimeError(
                "optimizer state leaf count mismatch (%d vs %d): "
                "different optimizer?"
                % (len(encs), len(self._mesh_state_leaves)))
        self._mesh_state_leaves = tuple(
            jax.device_put(jnp.asarray(_ckpt.decode_array(e)),
                           NamedSharding(mesh, spec))
            for e, spec in zip(encs, self._mesh_state_specs))
        self._step_count = int(payload["step_count"])
        self._opt.num_update = self._step_count
        _rng.set_state(payload["rng"])
        np.random.set_state(payload["numpy_global"])
        self._inflight.clear()
        return dict(payload["cursor"], step=self._step_count)

    # -- the compiled step -------------------------------------------------
    def _apply_groups(self, train_vals, states, grads, lr, t,
                      inv_scale=None, ok=None):
        """Optimizer update for every group — traced inside the step jit
        (single-process) or the update jit (dist split-step).  With the
        fused Pallas update enabled (docs/fusion.md) a group's update
        runs as ONE kernel pass over its flat f32 space instead of the
        unfused elementwise eqn chain; numerics mirror
        ``Optimizer.update`` exactly.  Mixed precision threads the
        loss-scale reciprocal and the finite flag through (``inv_scale``
        / ``ok`` f32 scalars): the fused kernel unscales + select-skips
        in the same pass, the unfused fallback spells the same algebra
        around ``functional_optimizer_update``."""
        from ..ops import fused_optimizer as _fused

        opt, groups = self._opt, self._groups
        fused_on = (_fused.fused_update_enabled()
                    and _fused.supports(opt) is not None)
        scaled = inv_scale is not None
        name_to_idx = {n: i for i, n in enumerate(self._train_names)}
        new_vals = [None] * len(train_vals)
        new_states = []

        def _fused_flat(gi, wf, gf):
            sf = jax.tree_util.tree_map(jnp.ravel, states[gi])
            kw = ({"inv_scale": inv_scale, "ok": ok} if scaled else {})
            nwf, nsf = _fused.fused_optimizer_update(
                opt, gi, wf.ravel(), gf.ravel(), sf, lr, t, **kw)
            ns = jax.tree_util.tree_map(
                lambda n, o: n.reshape(o.shape), nsf, states[gi])
            return nwf, ns

        def _unfused(gi, wf, gf):
            if not scaled:
                return functional_optimizer_update(
                    opt, gi, wf, gf, states[gi], lr, t)
            nw, ns = functional_optimizer_update(
                opt, gi, wf, gf * inv_scale, states[gi], lr, t)
            okb = ok > 0.0
            nw = jnp.where(okb, nw, wf)
            ns = jax.tree_util.tree_map(
                lambda n, o: jnp.where(okb, n, o), ns, states[gi])
            return nw, ns

        for gi, names in enumerate(groups):
            idxs = [name_to_idx[n] for n in names]
            if len(idxs) == 1:
                i = idxs[0]
                if fused_on and train_vals[i].dtype == jnp.float32:
                    nwf, ns = _fused_flat(gi, train_vals[i], grads[i])
                    nw = nwf.reshape(train_vals[i].shape)
                else:
                    nw, ns = _unfused(gi, train_vals[i], grads[i])
                new_vals[i] = nw
            else:
                # fused bucket: one flat update for the whole group
                # instead of len(group) small fusions — a single Pallas
                # pass when the fused kernels are enabled
                wf = jnp.concatenate(
                    [train_vals[i].ravel() for i in idxs])
                gf = jnp.concatenate([grads[i].ravel() for i in idxs])
                if fused_on and wf.dtype == jnp.float32:
                    nwf, ns = _fused_flat(gi, wf, gf)
                else:
                    nwf, ns = _unfused(gi, wf, gf)
                off = 0
                for i in idxs:
                    sz = train_vals[i].size
                    new_vals[i] = nwf[off:off + sz].reshape(
                        train_vals[i].shape)
                    off += sz
            new_states.append(ns)
        return tuple(new_vals), tuple(new_states)

    def _build_step(self):
        fwd = self._fwd
        if self._reduced:
            return jax.jit(self._reduced_pure_step(),
                           donate_argnums=(0, 1))

        n_acc = self._grad_accum
        if n_acc > 1:
            # microbatched spelling (grad_accum): left-fold sum of
            # per-microbatch grads (functional.accumulate_grads), ONE
            # optimizer update on the mean — the n_acc=1 spelling below
            # stays byte-identical to the historical traced program
            def pure_step(train_vals, states, aux_vals, x, y, key, lr,
                          t):
                def grad_of(tv, xi, yi):
                    def loss_of(t_):
                        outs, muts = fwd(t_, aux_vals, (xi, yi), key)
                        return outs[0], muts
                    return jax.value_and_grad(loss_of, has_aux=True)(tv)

                grads_sum, loss_sum, muts_stack = \
                    accumulate_grads(grad_of, train_vals, x, y, n_acc)
                grads = tuple(g / n_acc for g in grads_sum)
                loss_val = loss_sum / n_acc
                muts = tuple(m.mean(axis=0) for m in muts_stack)
                new_vals, new_states = self._apply_groups(
                    train_vals, states, grads, lr, t)
                return loss_val, new_vals, new_states, muts

            return jax.jit(pure_step, donate_argnums=(0, 1))

        def pure_step(train_vals, states, aux_vals, x, y, key, lr, t):
            def loss_of(tv):
                outs, muts = fwd(tv, aux_vals, (x, y), key)
                return outs[0], muts

            (loss_val, muts), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            new_vals, new_states = self._apply_groups(
                train_vals, states, grads, lr, t)
            return loss_val, new_vals, new_states, muts

        return jax.jit(pure_step, donate_argnums=(0, 1))

    def _reduced_pure_step(self):
        """Mixed-precision replicated spelling: the f32 ``train_vals``
        ARE the masters; they cast to the compute dtype at the forward
        boundary (so grads come back f32 through the cast transpose),
        the scaled loss drives the backward, and the optimizer update
        unscales + select-skips on the global finite flag — one kernel
        pass when fused (docs/precision.md)."""
        from .. import precision as _precision
        fwd, dtype = self._fwd, self._dtype

        def _to_compute(v):
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype,
                                                      jnp.floating):
                return v.astype(dtype)
            return v

        def pure_step(train_vals, states, aux_vals, x, y, key, lr, t,
                      scale, good, skipped):
            x_c = _to_compute(x)
            aux_c = tuple(_to_compute(a) for a in aux_vals)

            def loss_of(tv):
                tv_c = tuple(_to_compute(w) for w in tv)
                outs, muts = fwd(tv_c, aux_c, (x_c, y), key)
                raw = outs[0].astype(jnp.float32)
                return raw * scale, (raw, muts)

            (_, (loss_val, muts)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            fin = _precision.all_finite(grads)
            inv = (1.0 / scale).astype(jnp.float32)
            new_vals, new_states = self._apply_groups(
                train_vals, states, grads, lr, t,
                inv_scale=inv, ok=fin.astype(jnp.float32))
            new_scale, new_good = _precision.loss_scale_update(
                scale, good, fin)
            new_skipped = skipped + (1 - fin.astype(jnp.int32))
            muts = tuple(m.astype(jnp.float32) for m in muts)
            return (loss_val, new_vals, new_states, muts,
                    new_scale, new_good, new_skipped)

        return pure_step

    def _reduce_grads(self, grads):
        """Cross-replica gradient mean over the data axis.

        This is the step's ONE reduction point: explicit in the
        per-replica spelling (``_build_replica_step``, what the DST lint
        verifies); under ``jax.jit`` + ``NamedSharding`` the compiler
        inserts the equivalent psum automatically because the loss is a
        mean over the batch-sharded axis.  Removing this call is exactly
        the "gradient psum removed" bug class: DST001 fires per
        parameter (tests/test_analysis.py)."""
        return tuple(jax.lax.pmean(g, self._data_axis) for g in grads)

    def _build_replica_step(self):
        """Per-replica spelling of the compiled step for static analysis:
        the SAME forward/loss/optimizer code as ``_build_step``, seen
        from one shard of the data axis, with the cross-replica
        collectives written out (grads, the reported loss, and BatchNorm
        batch statistics are all global under GSPMD).  Traced with
        ``jax.make_jaxpr(axis_env=[(data_axis, K)])`` — no hardware, no
        compilation — by ``lint()``/``cost_report()`` and the
        ``python -m mxnet_tpu.analysis --cost`` budget models."""
        fwd = self._fwd
        axis = self._data_axis
        if self._reduced:
            from .. import precision as _precision
            dtype = self._dtype

            def _to_compute(v):
                if hasattr(v, "dtype") and jnp.issubdtype(
                        v.dtype, jnp.floating):
                    return v.astype(dtype)
                return v

            def replica_step(train_vals, states, aux_vals, x, y, key,
                             lr, t):
                # analysis twin of the reduced jitted step, seeded with
                # the neutral loss-scale constants (scale=1 keeps the
                # traced algebra identical; the live scale only changes
                # a scalar multiply).  8-arg so lint_trainer/cost_report
                # keep their one calling convention.
                scale = jnp.float32(1.0)
                x_c = _to_compute(x)
                aux_c = tuple(_to_compute(a) for a in aux_vals)

                def loss_of(tv):
                    tv_c = tuple(_to_compute(w) for w in tv)
                    outs, muts = fwd(tv_c, aux_c, (x_c, y), key)
                    raw = outs[0].astype(jnp.float32)
                    return raw * scale, (raw, muts)

                (_, (loss_val, muts)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_vals)
                # grads are f32 through the cast transpose — the
                # collective reduces f32 (tightened DST004 contract)
                grads = self._reduce_grads(grads)
                loss_val = jax.lax.pmean(loss_val, axis)
                muts = tuple(jax.lax.pmean(m.astype(jnp.float32), axis)
                             for m in muts)
                fin = _precision.all_finite(grads)
                inv = (1.0 / scale).astype(jnp.float32)
                new_vals, new_states = self._apply_groups(
                    train_vals, states, grads, lr, t,
                    inv_scale=inv, ok=fin.astype(jnp.float32))
                return loss_val, new_vals, new_states, muts

            return replica_step

        n_acc = self._grad_accum
        if n_acc > 1:
            # analysis twin of the grad_accum jitted step: the SAME
            # accumulate_grads spelling, then the step's ONE gradient
            # reduction — accumulation happens per replica, the
            # collective count is unchanged (DST001 still counts one
            # pmean per trainable)
            def replica_step(train_vals, states, aux_vals, x, y, key,
                             lr, t):
                def grad_of(tv, xi, yi):
                    def loss_of(t_):
                        outs, muts = fwd(t_, aux_vals, (xi, yi), key)
                        return outs[0], muts
                    return jax.value_and_grad(loss_of, has_aux=True)(tv)

                grads_sum, loss_sum, muts_stack = \
                    accumulate_grads(grad_of, train_vals, x, y, n_acc)
                grads = tuple(g / n_acc for g in grads_sum)
                loss_val = loss_sum / n_acc
                muts = tuple(m.mean(axis=0) for m in muts_stack)
                grads = self._reduce_grads(grads)
                loss_val = jax.lax.pmean(loss_val, axis)
                muts = tuple(jax.lax.pmean(m, axis) for m in muts)
                new_vals, new_states = self._apply_groups(
                    train_vals, states, grads, lr, t)
                return loss_val, new_vals, new_states, muts

            return replica_step

        def replica_step(train_vals, states, aux_vals, x, y, key, lr, t):
            def loss_of(tv):
                outs, muts = fwd(tv, aux_vals, (x, y), key)
                return outs[0], muts

            (loss_val, muts), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            grads = self._reduce_grads(grads)
            loss_val = jax.lax.pmean(loss_val, axis)
            muts = tuple(jax.lax.pmean(m, axis) for m in muts)
            new_vals, new_states = self._apply_groups(
                train_vals, states, grads, lr, t)
            return loss_val, new_vals, new_states, muts

        return replica_step

    # -- static analysis hooks (mxnet_tpu.analysis) ------------------------
    def lint(self, data_shape=None, label_shape=None,
             data_dtype="float32", label_dtype="int32",
             declared_axis_size=None, disable=()):
        """DST lint of the distributed step (analysis/dist_lint.py):
        every trainable gradient reduced over the data axis exactly
        once, sharding-spec consistency, collective dtype promotion,
        baked step constants.  Hardware-free; returns Finding records.
        A zero=1 trainer routes to the mixed-axis rules over the real
        runtime spelling instead (``zero_report``); a mesh_plan trainer
        to ``mesh_report``."""
        if self._plan is not None:
            _, findings, _ = self.mesh_report(data_shape=data_shape)
            from ..analysis.findings import filter_findings
            return filter_findings(findings, disable)
        if self._zero:
            _, findings, _ = self.zero_report(
                data_shape=data_shape, label_shape=label_shape,
                data_dtype=data_dtype, label_dtype=label_dtype,
                declared_axis_size=declared_axis_size)
            from ..analysis.findings import filter_findings
            return filter_findings(findings, disable)
        from ..analysis.dist_lint import lint_trainer
        return lint_trainer(self, data_shape=data_shape,
                            label_shape=label_shape,
                            data_dtype=data_dtype,
                            label_dtype=label_dtype,
                            declared_axis_size=declared_axis_size,
                            disable=disable)

    def cost_report(self, data_shape=None, label_shape=None,
                    data_dtype="float32", label_dtype="int32",
                    declared_axis_size=None):
        """Static CostReport of one training step (analysis/cost.py):
        FLOPs/bytes/peak-HBM of the full-batch program (params + states
        donated, batch host-fed, loss fetched) plus per-axis collective
        bytes from the per-replica trace.  Never executes or compiles.
        A zero=1 trainer reports over the real runtime spelling
        (``zero_report``), whose collectives are explicit."""
        import numpy as _onp

        from ..analysis import cost as _cost

        if self._plan is not None:
            report, _, _ = self.mesh_report(data_shape=data_shape)
            return report
        if self._zero:
            report, _, _ = self.zero_report(
                data_shape=data_shape, label_shape=label_shape,
                data_dtype=data_dtype, label_dtype=label_dtype,
                declared_axis_size=declared_axis_size)
            return report

        if not self._ready:
            if data_shape is None:
                raise ValueError(
                    "trainer has not stepped yet: pass data_shape (and "
                    "label_shape)")
            x0 = NDArray(jnp.zeros(tuple(data_shape),
                                   _onp.dtype(data_dtype)))
            y0 = NDArray(jnp.zeros(
                tuple(label_shape or (data_shape[0],)),
                _onp.dtype(label_dtype)))
            self._setup(x0, y0)
        data_shape = tuple(data_shape)
        label_shape = tuple(label_shape or (data_shape[0],))
        train_vals = tuple(self._params_by_name[n].data()._data
                           for n in self._train_names)
        aux_vals = tuple(self._params_by_name[n].data()._data
                         for n in self._aux_names)
        states = tuple(self._states_raw)
        x = jax.ShapeDtypeStruct(data_shape, _onp.dtype(data_dtype))
        y = jax.ShapeDtypeStruct(label_shape, _onp.dtype(label_dtype))
        key = jax.ShapeDtypeStruct((2,), _onp.uint32)
        fwd = self._fwd

        def pure_step(train_vals, states, aux_vals, x, y, key, lr, t):
            def loss_of(tv):
                outs, muts = fwd(tv, aux_vals, (x, y), key)
                return outs[0], muts

            (loss_val, muts), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            new_vals, new_states = self._apply_groups(
                train_vals, states, grads, lr, t)
            return loss_val, new_vals, new_states, muts

        report = _cost.analyze_fn(
            pure_step, train_vals, states, aux_vals, x, y, key,
            jnp.float32(0.01), jnp.int32(1),
            donate_argnums=(0, 1), host_argnums=(3, 4))
        # loss is the only fetched output; new params/states stay put
        report.transfer_d2h_bytes = 4
        # collective bytes from the per-replica spelling (the full-batch
        # jaxpr has no explicit collectives — GSPMD inserts them)
        axis_sizes = dict(zip(self._mesh.axis_names,
                              self._mesh.devices.shape))
        ksize = int(declared_axis_size
                    or axis_sizes.get(self._data_axis, 1))
        shard = max(data_shape[0] // max(ksize, 1), 1)
        xs = jax.ShapeDtypeStruct((shard,) + data_shape[1:],
                                  _onp.dtype(data_dtype))
        ys = jax.ShapeDtypeStruct((shard,) + label_shape[1:],
                                  _onp.dtype(label_dtype))
        try:
            rep = _cost.analyze_fn(
                self._build_replica_step(), train_vals, states, aux_vals,
                xs, ys, key, jnp.float32(0.01), jnp.int32(1),
                axis_env=[(self._data_axis, ksize)])
            report.collective_bytes_per_axis = \
                rep.collective_bytes_per_axis
        except Exception:
            pass
        report.axis_sizes = {self._data_axis: ksize}
        return report

    def shard_report(self, data_shape=None, label_shape=None,
                     data_dtype="float32", label_dtype="int32",
                     declared_axis_size=None):
        """mxshard global-view report of one training step
        (analysis/shard_prop.py): the full-batch step program with the
        trainer's declared input shardings (params/states per
        ``param_spec_fn``, batch over the data axis) propagated
        GSPMD-style — the returned schedule holds the collectives the
        compiler would INSERT (the gradient psum appears as an inferred
        partial-sum reduction, without the per-replica spelling) plus
        any forced activation reshards (DST010 material).  Hardware-
        free; never executes or compiles.  A mesh_plan trainer returns
        its ``mesh_report`` ShardReport instead — the per-replica
        EXPLICIT mixed-axis schedule, priced per axis."""
        import numpy as _onp

        from ..analysis import shard_prop as _sp

        if self._plan is not None:
            _, _, shard = self.mesh_report(data_shape=data_shape)
            return shard

        if not self._ready:
            if data_shape is None:
                raise ValueError(
                    "trainer has not stepped yet: pass data_shape (and "
                    "label_shape)")
            x0 = NDArray(jnp.zeros(tuple(data_shape),
                                   _onp.dtype(data_dtype)))
            y0 = NDArray(jnp.zeros(
                tuple(label_shape or (data_shape[0],)),
                _onp.dtype(label_dtype)))
            self._setup(x0, y0)
        data_shape = tuple(data_shape)
        label_shape = tuple(label_shape or (data_shape[0],))
        train_vals = tuple(self._params_by_name[n].data()._data
                           for n in self._train_names)
        aux_vals = tuple(self._params_by_name[n].data()._data
                         for n in self._aux_names)
        states = tuple(self._states_raw)
        x = jax.ShapeDtypeStruct(data_shape, _onp.dtype(data_dtype))
        y = jax.ShapeDtypeStruct(label_shape, _onp.dtype(label_dtype))
        key = jax.ShapeDtypeStruct((2,), _onp.uint32)
        fwd = self._fwd

        def pure_step(train_vals, states, aux_vals, x, y, key, lr, t):
            def loss_of(tv):
                outs, muts = fwd(tv, aux_vals, (x, y), key)
                return outs[0], muts

            (loss_val, muts), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            new_vals, new_states = self._apply_groups(
                train_vals, states, grads, lr, t)
            return loss_val, new_vals, new_states, muts

        closed = jax.make_jaxpr(pure_step)(
            train_vals, states, aux_vals, x, y, key,
            jnp.float32(0.01), jnp.int32(1))
        axis_sizes = dict(zip(self._mesh.axis_names,
                              self._mesh.devices.shape))
        axis_sizes[self._data_axis] = int(
            declared_axis_size or axis_sizes.get(self._data_axis, 1))
        mesh = _sp.MeshSpec(axis_sizes)
        # flat in_specs follow the step's arg order: params get their
        # PartitionSpec, optimizer states their group sharding, the
        # batch shards over the data axis, everything else replicates
        in_specs = [self._param_spec_fn(
            n, self._params_by_name[n].shape) for n in self._train_names]
        for gi, raw in enumerate(self._states_raw):
            spec = self._group_shardings[gi].spec
            in_specs += [spec] * len(jax.tree_util.tree_leaves(raw))
        in_specs += [self._param_spec_fn(
            n, self._params_by_name[n].shape) for n in self._aux_names]
        in_specs += [PartitionSpec(self._data_axis),
                     PartitionSpec(self._data_axis), None, None, None]
        return _sp.propagate(closed, mesh, in_specs,
                             subject="DataParallelTrainer")

    def fusion_report(self, data_shape=None, label_shape=None,
                      data_dtype="float32", label_dtype="int32",
                      declared_axis_size=None):
        """mxfuse FusionReport of one training step
        (``analysis/fusion.py``): the step tape segmented into fusable
        chains ranked by modeled bytes-saved-if-fused.  Hardware-free;
        a zero=1 trainer analyzes the runtime reduce-scatter/update/
        all-gather spelling, a mesh_plan trainer the mesh-tier replica
        step.  When telemetry is armed and the top chain covers more
        than ``FUSION_HINT_MIN_PCT`` of step bytes, the dispatch /
        collective phases are context-tagged ``fusable`` so ``telemetry
        doctor`` names the fusion knob (docs/fusion.md)."""
        import numpy as _onp

        from ..analysis import fusion as _fusion

        if self._plan is not None:
            _, _, shard = self.mesh_report(data_shape=data_shape)
            closed, axis_sizes = shard._fusion_ctx
            report = _fusion.fusion_from_jaxpr(closed,
                                               axis_sizes=axis_sizes)
        elif self._zero:
            _, _, shard = self.zero_report(
                data_shape=data_shape, label_shape=label_shape,
                data_dtype=data_dtype, label_dtype=label_dtype,
                declared_axis_size=declared_axis_size)
            closed, axis_sizes = shard._fusion_ctx
            report = _fusion.fusion_from_jaxpr(closed,
                                               axis_sizes=axis_sizes)
        else:
            if not self._ready:
                if data_shape is None:
                    raise ValueError(
                        "trainer has not stepped yet: pass data_shape "
                        "(and label_shape)")
                x0 = NDArray(jnp.zeros(tuple(data_shape),
                                       _onp.dtype(data_dtype)))
                y0 = NDArray(jnp.zeros(
                    tuple(label_shape or (data_shape[0],)),
                    _onp.dtype(label_dtype)))
                self._setup(x0, y0)
            data_shape = tuple(data_shape)
            label_shape = tuple(label_shape or (data_shape[0],))
            train_vals = tuple(self._params_by_name[n].data()._data
                               for n in self._train_names)
            aux_vals = tuple(self._params_by_name[n].data()._data
                             for n in self._aux_names)
            states = tuple(self._states_raw)
            x = jax.ShapeDtypeStruct(data_shape, _onp.dtype(data_dtype))
            y = jax.ShapeDtypeStruct(label_shape,
                                     _onp.dtype(label_dtype))
            key = jax.ShapeDtypeStruct((2,), _onp.uint32)
            fwd = self._fwd

            def pure_step(train_vals, states, aux_vals, x, y, key, lr,
                          t):
                def loss_of(tv):
                    outs, muts = fwd(tv, aux_vals, (x, y), key)
                    return outs[0], muts

                (loss_val, muts), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_vals)
                new_vals, new_states = self._apply_groups(
                    train_vals, states, grads, lr, t)
                return loss_val, new_vals, new_states, muts

            report = _fusion.fusion_from_fn(
                pure_step, train_vals, states, aux_vals, x, y, key,
                jnp.float32(0.01), jnp.int32(1))

        self._last_fusion_report = report
        # doctor follow-through: a dominant dispatch/collective phase
        # plus a big fusable chain means the fusion knob is the hint
        top = report.top_chain_pct
        if _tele._ENABLED and top > _fusion.FUSION_HINT_MIN_PCT:
            attr = _tele.attribution()
            context = attr.snapshot().get("context") or {}
            for phase in ("dispatch", "collective_or_ps"):
                if phase not in context:
                    attr.set_context(phase, "fusable")
        return report

    def _build_grad_step(self):
        """Dist split-step, part 1: loss + local gradients (no update) —
        the grads cross the process boundary through the kvstore between
        the two jits (reference: executor backward -> kv.push,
        python/mxnet/module/executor_group.py:583)."""
        fwd = self._fwd

        def pure_grads(train_vals, aux_vals, x, y, key):
            def loss_of(tv):
                outs, muts = fwd(tv, aux_vals, (x, y), key)
                return outs[0], muts

            (loss_val, muts), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_vals)
            # flatten inside the jit: the host sees one fused f32 vector
            # (grads + the loss scalar riding along) ready to push
            flat = jnp.concatenate(
                [g.ravel().astype(jnp.float32) for g in grads]
                + [loss_val.reshape(1).astype(jnp.float32)])
            return flat, muts

        return jax.jit(pure_grads)

    def _build_update_step(self):
        """Dist split-step, part 2: scale the pulled grad-sum, split it
        back per-param, apply the optimizer — all in one jit (reference:
        kv.pull -> updater, python/mxnet/model.py:157)."""
        sizes = self._flat_sizes
        scale = 1.0 / self._kv.num_workers

        def pure_update(train_vals, states, flat_sum, lr, t):
            mean = flat_sum * scale
            grads, off = [], 0
            for tv, n in zip(train_vals, sizes):
                grads.append(mean[off:off + n].reshape(tv.shape)
                             .astype(tv.dtype))
                off += n
            new_vals, new_states = self._apply_groups(
                train_vals, states, tuple(grads), lr, t)
            return mean[-1], new_vals, new_states

        return jax.jit(pure_update, donate_argnums=(0, 1))

    # -- public API --------------------------------------------------------
    @property
    def mesh(self):
        return self._mesh

    @property
    def batch_sharding(self):
        """The NamedSharding step inputs are placed with (batch sharded
        over the data axis; under a MeshPlan, ``(batch, tokens)`` over
        ``data × sequence``).  A feeder that pre-places batches with this
        sharding (``mx.io.PrefetchToDeviceIter``) hits ``step``'s
        fast path: the transfer is reused, not redone."""
        if self._plan is not None:
            self._ensure_mesh()
            return NamedSharding(self._mesh, self._plan.batch_spec())
        return NamedSharding(self._mesh, PartitionSpec(self._data_axis))

    def _put_batch(self, arr, sharding):
        """``device_put`` with a fast path: a committed ``jax.Array``
        already laid out per ``sharding`` (the prefetcher's work) is used
        as-is instead of being re-put — ``device_put`` is cheap for a
        matching layout but not free (it still walks shards and can copy
        on layout mismatch), and skipping it keeps the prefetch transfer
        the only one."""
        raw = arr._data if isinstance(arr, NDArray) else arr
        if isinstance(raw, jax.Array) and getattr(raw, "committed", False):
            try:
                if raw.sharding.is_equivalent_to(sharding, raw.ndim):
                    return raw
            except (AttributeError, TypeError):
                if raw.sharding == sharding:
                    return raw
        if not isinstance(raw, jax.Array):
            raw = np.asarray(raw)
        return jax.device_put(raw, sharding)

    def _track_inflight(self, loss_val):
        """Run-ahead bookkeeping: ring the dispatched step's output and
        apply backpressure — wait on the OLDEST in-flight step when the
        ring exceeds ``engine.bulk_size()``.  Dispatch order never
        changes, so any window size is bitwise-identical; only where the
        host blocks moves."""
        self._inflight.append(loss_val)
        limit = engine_mod.bulk_size()
        while len(self._inflight) > limit:
            oldest = self._inflight.popleft()
            t0 = time.perf_counter()
            try:
                oldest.block_until_ready()
            except AttributeError:
                pass
            waited = time.perf_counter() - t0
            self.dispatch_stats.on_backpressure(waited)
            # sub-20us "waits" are block_until_ready call overhead on an
            # already-finished step, not device backpressure — skipping
            # them keeps the armed per-step cost inside the bench budget
            if waited > 2e-5 and _tele._ENABLED:
                _tele.attribution().add_phase("runahead_stall", waited)
        self.dispatch_stats.on_dispatch(len(self._inflight))

    def flush(self):
        """Drain the in-flight ring: block until every dispatched step has
        executed.  Called by ``engine.flush()``/``bulk()`` exit and at
        ``fit`` epoch boundaries; after it returns, params/optimizer
        states are fully materialized (donation already retired)."""
        t0 = time.perf_counter()
        while self._inflight:
            oldest = self._inflight.popleft()
            try:
                oldest.block_until_ready()
            except AttributeError:
                pass
        waited = time.perf_counter() - t0
        if waited > 0:
            self.dispatch_stats.on_backpressure(waited)
            if _tele._ENABLED:
                _tele.attribution().add_phase("runahead_stall", waited)
        if self._reduced and self._ready:
            # everything dispatched has retired, so the loss-scale
            # scalars are cheap to read: publish the live scale and any
            # newly-skipped steps (docs/observability.md)
            from .. import precision as _precision
            skipped = int(self._ls_skipped)
            _precision.record_loss_scale(
                float(self._ls_scale),
                skipped - self._ls_reported_skipped)
            self._ls_reported_skipped = skipped

    def step(self, data, label):
        """Run one training step; returns the (scalar) loss NDArray.

        Non-blocking by construction: the jitted step is dispatched into
        XLA's async queue and the loss comes back as a lazy device value —
        the host only blocks when the engine's run-ahead window
        (``mx.engine.set_bulk_size``) is full, and then on the *oldest*
        in-flight step (backpressure), not the newest."""
        from .. import _rng
        if self._plan is not None:
            return self._step_mesh_tier(data, label)
        if not self._ready:
            self._setup(data, label)

        # per-step attribution (docs/observability.md "Performance
        # doctor"): the on_step mark closes the previous step's window —
        # everything phase-timed since the last mark (backpressure,
        # metric drains, checkpoints, the fit loop's input wait)
        # reconciles against that window's wall clock — and stores the
        # flight-ring progress cursor (the SIGKILLed-worker "how far did
        # it train" field).  One bool check when telemetry is off (the
        # <=1% bench gate).
        tele_on = _tele._ENABLED
        attr = _tele.attribution() if tele_on else None
        if tele_on:
            attr.on_step(self._step_count + 1)

        batch_sh = self.batch_sharding
        t0 = time.perf_counter() if tele_on else 0.0
        x = self._put_batch(data, batch_sh)
        y = self._put_batch(label, batch_sh)
        if tele_on:
            t1 = time.perf_counter()
            attr.add_phase("h2d_transfer", t1 - t0)

        self._step_count += 1
        # chaos probe: a scheduled fault (SIGKILL at step k, injected
        # failure, stall) fires HERE — before dispatch, so a killed step
        # never half-applies (tests/test_resilience.py end-to-end crash)
        _chaos.maybe_inject("trainer.step", self._step_count, ctx=self)
        self._opt.num_update = self._step_count
        lr_host = (self._opt.lr_scheduler(self._step_count)
                   if self._opt.lr_scheduler else self._opt.lr)
        train_vals = tuple(self._params_by_name[n].data()._data
                           for n in self._train_names)
        aux_vals = tuple(self._params_by_name[n].data()._data
                         for n in self._aux_names)
        rng = _rng.next_key()

        if self._kv is not None:
            loss_val, new_vals, new_states, muts = self._dist_step(
                train_vals, aux_vals, x, y, rng, lr_host)
            self._states_raw = list(new_states)
        elif self._zero:
            # split step: grads + reduce-scatter, then sharded update +
            # all-gather — states updated inside (they live as one
            # sharded flat tree, not per-group)
            loss_val, new_vals, muts = self._zero_step(
                train_vals, aux_vals, x, y, rng, lr_host,
                tele_on, attr, t1 if tele_on else 0.0)
        else:
            # jax.jit itself retraces and caches per input shape/dtype
            if self._step_fn is None:
                self._step_fn = self._build_step()
                if tele_on and self._grad_accum > 1:
                    attr.set_context("dispatch", "grad_accum")
            if self._reduced:
                (loss_val, new_vals, new_states, muts, self._ls_scale,
                 self._ls_good, self._ls_skipped) = self._step_fn(
                    train_vals, tuple(self._states_raw), aux_vals, x, y,
                    rng, jnp.float32(lr_host),
                    jnp.int32(self._step_count), self._ls_scale,
                    self._ls_good, self._ls_skipped)
            else:
                loss_val, new_vals, new_states, muts = self._step_fn(
                    train_vals, tuple(self._states_raw), aux_vals, x, y,
                    rng, jnp.float32(lr_host),
                    jnp.int32(self._step_count))
            self._states_raw = list(new_states)
            if tele_on:
                # "dispatch" spans from the batch being device-ready to
                # the step program dispatched — step bookkeeping (arg
                # tuples, lr) is host dispatch work and bills here
                attr.add_phase("dispatch", time.perf_counter() - t1)

        for name, val in zip(self._train_names, new_vals):
            self._params_by_name[name]._data._set_data(val)
        for name, val in zip(self._fwd.mut_names or (), muts):
            self._params_by_name[name]._data._set_data(val)
        self._track_inflight(loss_val)
        return NDArray(loss_val)

    # -- checkpoint / resume (mxnet_tpu.resilience) ------------------------
    def save_checkpoint(self, directory, epoch=None, nbatch=None, keep=3):
        """Atomic snapshot of the full training state: params + optimizer
        states + RNG + iterator cursor (``epoch``/``nbatch``), written
        via ``resilience.checkpoint`` (write-rename — a crash mid-save
        leaves the previous snapshot intact).  The in-flight run-ahead
        ring is flushed FIRST, so a snapshot taken inside an
        ``engine.bulk`` window never records run-ahead state — the
        crash-mid-window case resumes from fully-materialized params."""
        from .. import _rng
        from ..resilience import checkpoint as _ckpt
        if not self._ready:
            raise RuntimeError("trainer has not stepped yet: nothing to "
                               "checkpoint")
        self.flush()
        # attribution: the flush above bills its wait to runahead_stall;
        # only the encode + atomic write below is checkpoint time (the
        # phases stay disjoint, so per-window sums reconcile)
        t_ckpt = time.perf_counter() if _tele._ENABLED else 0.0
        if self._plan is not None:
            path = self._save_mesh(directory, epoch=epoch,
                                   nbatch=nbatch, keep=keep)
            if _tele._ENABLED:
                _tele.attribution().add_phase(
                    "checkpoint", time.perf_counter() - t_ckpt)
            return path
        if self._zero:
            path = self._save_sharded(directory, epoch=epoch,
                                      nbatch=nbatch, keep=keep)
            if _tele._ENABLED:
                _tele.attribution().add_phase(
                    "checkpoint", time.perf_counter() - t_ckpt)
            return path
        params = {name: _ckpt.encode_array(p.data()._data)
                  for name, p in self._params_by_name.items()}
        states = []
        for raw in self._states_raw:
            leaves = jax.tree_util.tree_leaves(raw)
            states.append([_ckpt.encode_array(v) for v in leaves])
        payload = {
            "params": params,
            "states": states,
            "step_count": self._step_count,
            "rng": _rng.get_state(),
            "numpy_global": np.random.get_state(),
            "cursor": {"epoch": epoch, "nbatch": nbatch},
            "setup_desc": self._setup_desc,
            "groups": [list(g) for g in self._groups],
        }
        if self._reduced:
            payload["loss_scale"] = {
                "scale": float(self._ls_scale),
                "good_steps": int(self._ls_good),
                "skipped": int(self._ls_skipped),
            }
        # provenance digest over NAME-CANONICALIZED content: gluon
        # gensyms shift per process (dense0 vs dense12 for the same
        # architecture — the positional-mapping case restore_checkpoint
        # already handles), so the digest maps param names to their
        # position before hashing.  Two reruns of the same training
        # therefore name the same bytes — what makes promotion audit
        # trails replayable.
        order = {name: "p%05d" % i for i, name in enumerate(params)}
        canon = dict(payload,
                     params={order[n]: enc for n, enc in params.items()},
                     groups=[[order[n] for n in g] for g in self._groups])
        path = _ckpt.save_checkpoint(
            directory, payload, self._step_count, keep=keep,
            provenance={"epoch": epoch, "train_run_id": self.run_id,
                        "digest": _ckpt.payload_digest(canon)})
        if _tele._ENABLED:
            _tele.attribution().add_phase(
                "checkpoint", time.perf_counter() - t_ckpt)
        return path

    def _save_sharded(self, directory, epoch=None, nbatch=None, keep=3):
        """Shard-parallel snapshot of a zero=1 trainer: the rank-
        agnostic payload (params, RNG, cursor, flat-layout plan) rides
        the manifest; every rank's 1/K optimizer-state slice is its own
        atomically-installed shard file (``resilience.checkpoint``
        sharded format, docs/elastic.md).  A fleet of a *different*
        size restores by reassembling the full flat state and
        re-sharding deterministically."""
        from .. import _rng
        from ..resilience import checkpoint as _ckpt
        plan = self._zero_plan
        params = {name: _ckpt.encode_array(p.data()._data)
                  for name, p in self._params_by_name.items()}
        leaves = [np.asarray(v) for v in self._zero_leaves()]
        payload = {
            "params": params,
            "step_count": self._step_count,
            "rng": _rng.get_state(),
            "numpy_global": np.random.get_state(),
            "cursor": {"epoch": epoch, "nbatch": nbatch},
            "setup_desc": self._setup_desc,
            "zero_plan": plan.describe(),
            "state_leaf_count": len(leaves),
        }
        master = None
        if self._reduced:
            # the f32 masters shard exactly like the state leaves; the
            # loss-scale machine state is three host scalars.  Both must
            # survive resize-on-resume BITWISE (docs/precision.md).
            master = np.asarray(self._zero_master)
            payload["has_master"] = True
            payload["loss_scale"] = {
                "scale": float(self._ls_scale),
                "good_steps": int(self._ls_good),
                "skipped": int(self._ls_skipped),
            }
        shards = []
        for r in range(plan.k):
            sl = slice(r * plan.shard, (r + 1) * plan.shard)
            rec = {"states": [_ckpt.encode_array(leaf[sl])
                              for leaf in leaves]}
            if master is not None:
                rec["master"] = _ckpt.encode_array(master[sl])
            shards.append(rec)
        # provenance digest over NAME-CANONICALIZED content (the
        # monolithic discipline): gensym-shifted reruns name the same
        # bytes, and the digest covers the FULL state — independent of
        # the fleet size it happens to be sharded at
        order = {name: "p%05d" % i for i, name in enumerate(params)}
        canon = dict(payload,
                     params={order[n]: enc for n, enc in params.items()},
                     zero_plan=dict(plan.describe(),
                                    names=[order[n] for n in
                                           plan.names]))
        canon.pop("state_leaf_count", None)
        canon["full_state"] = [
            _ckpt.encode_array(leaf[:plan.total]) for leaf in leaves]
        if master is not None:
            canon["full_master"] = _ckpt.encode_array(
                master[:plan.total])
        for key in ("k", "padded", "shard"):
            canon["zero_plan"].pop(key, None)
        return _ckpt.save_sharded_checkpoint(
            directory, payload, shards, self._step_count, keep=keep,
            provenance={"epoch": epoch, "train_run_id": self.run_id,
                        "digest": _ckpt.payload_digest(canon)})

    def _restore_sharded(self, rec):
        """Restore a sharded-checkpoint record into this (zero=1)
        trainer, re-sharding the optimizer state for the CURRENT axis
        size: shards are concatenated in rank order, the zero padding
        tail truncated at the recorded ``total`` (provably zero — see
        ``parallel/zero.py``), re-padded for the new K and placed
        ``P(data)``-sharded.  1→2→4→1 round-trips bitwise."""
        from .. import _rng
        from ..resilience import checkpoint as _ckpt
        payload = rec["payload"]
        if not self._ready:
            dshape, ddt = payload["setup_desc"]["data"]
            lshape, ldt = payload["setup_desc"]["label"]
            self._setup(NDArray(jnp.zeros(dshape, np.dtype(ddt))),
                        NDArray(jnp.zeros(lshape, np.dtype(ldt))))
        if not self._zero:
            raise RuntimeError(
                "sharded checkpoint (ZeRO-1 optimizer shards) cannot "
                "restore into a zero=0 trainer — construct with zero=1")
        mapping = self._map_checkpoint_params(payload["params"])
        for cn, enc in payload["params"].items():
            name = mapping[cn]
            p = self._params_by_name[name]
            p._data._set_data(jax.device_put(
                jnp.asarray(_ckpt.decode_array(enc)),
                self._param_shardings[name]))
        plan_old = payload["zero_plan"]
        plan = self._zero_plan
        if int(plan_old["total"]) != plan.total:
            raise RuntimeError(
                "sharded checkpoint's flat parameter space has %d "
                "elements, this trainer's has %d — different model"
                % (int(plan_old["total"]), plan.total))
        n_leaves = int(payload["state_leaf_count"])
        cur_leaves = self._zero_leaves()
        if n_leaves != len(cur_leaves):
            raise RuntimeError(
                "optimizer state leaf count mismatch (%d vs %d): "
                "different optimizer?" % (n_leaves, len(cur_leaves)))
        if bool(payload.get("has_master")) != bool(self._reduced):
            raise RuntimeError(
                "mixed-precision mismatch: checkpoint %s f32 masters "
                "but this trainer was constructed with dtype=%r"
                % ("has" if payload.get("has_master") else "has no",
                   str(jnp.dtype(self._dtype))))
        from . import zero as _zero
        state_sh = self._group_shardings[0]
        new_leaves = []
        for li in range(n_leaves):
            full = _zero.reassemble_state(
                [_ckpt.decode_array(sh["states"][li])
                 for sh in rec["shards"]], plan.total)
            arr = np.zeros((plan.padded,), np.float32)
            arr[:plan.total] = full
            new_leaves.append(jax.device_put(jnp.asarray(arr), state_sh))
        self._states_raw = [jax.tree_util.tree_unflatten(
            self._zero_treedef, new_leaves)]
        if self._reduced:
            # masters restore BITWISE through the same reassemble/re-pad
            # path as the state leaves; live params are then re-derived
            # by exact cast so the param == cast(master) invariant holds
            # across any save-K -> restore-K' resize
            full_m = _zero.reassemble_state(
                [_ckpt.decode_array(sh["master"])
                 for sh in rec["shards"]], plan.total)
            arr = np.zeros((plan.padded,), np.float32)
            arr[:plan.total] = full_m
            self._zero_master = jax.device_put(jnp.asarray(arr),
                                               state_sh)
            vals = _zero._unflatten(jnp.asarray(
                arr.astype(np.float32)), plan, jnp)
            for name, val in zip(self._train_names, vals):
                self._params_by_name[name]._data._set_data(
                    jax.device_put(val.astype(self._dtype),
                                   self._param_shardings[name]))
            ls = payload["loss_scale"]
            self._ls_scale = jnp.asarray(ls["scale"], jnp.float32)
            self._ls_good = jnp.asarray(ls["good_steps"], jnp.int32)
            self._ls_skipped = jnp.asarray(ls["skipped"], jnp.int32)
            self._ls_reported_skipped = int(ls["skipped"])
        self._step_count = int(payload["step_count"])
        self._opt.num_update = self._step_count
        _rng.set_state(payload["rng"])
        np.random.set_state(payload["numpy_global"])
        self._inflight.clear()
        return dict(payload["cursor"], step=self._step_count)

    def _map_checkpoint_params(self, params_ckpt):
        """checkpoint-name -> live-name mapping: exact names when they
        match, else positional (gluon gensyms shift per process) with a
        per-param shape check — a genuinely different model fails."""
        names_ckpt = list(params_ckpt)
        names_cur = list(self._params_by_name)
        if set(names_ckpt) == set(names_cur):
            return {n: n for n in names_ckpt}
        if len(names_ckpt) == len(names_cur):
            mapping = dict(zip(names_ckpt, names_cur))
            for cn, name in mapping.items():
                shape = tuple(params_ckpt[cn][2])
                cur = tuple(int(d) for d in
                            self._params_by_name[name].shape)
                if shape != cur:
                    raise RuntimeError(
                        "checkpoint param %r %r does not match model "
                        "param %r %r (different architecture)"
                        % (cn, shape, name, cur))
            return mapping
        raise RuntimeError(
            "checkpoint has %d params, model has %d — different "
            "architecture" % (len(names_ckpt), len(names_cur)))

    def restore_checkpoint(self, path_or_dir):
        """Restore a :meth:`save_checkpoint` snapshot (a file, or a
        directory whose newest loadable checkpoint is taken).  Re-runs
        setup from the recorded batch geometry when the trainer has not
        stepped yet, so a *fresh* trainer resumes standalone.  Restores
        params/optimizer states onto their shardings, the step counter
        and the RNG state — with a deterministic data iterator the
        continued run is bitwise-identical to the uncrashed one
        (tests/test_resilience.py).  Returns the cursor dict
        (``epoch``/``nbatch``/``step``)."""
        import os as _os

        from .. import _rng
        from ..resilience import checkpoint as _ckpt
        if _os.path.isdir(path_or_dir):
            if self._plan is not None:
                found = _ckpt.latest_checkpoint(path_or_dir)
                if found is None:
                    raise FileNotFoundError(
                        "no loadable checkpoint under %r"
                        % (path_or_dir,))
                return self._restore_mesh(found[1])
            if self._zero:
                found = _ckpt.latest_sharded_checkpoint(path_or_dir)
                if found is None:
                    raise FileNotFoundError(
                        "no loadable sharded checkpoint (manifest) "
                        "under %r" % (path_or_dir,))
                return self._restore_sharded(found[1])
            found = _ckpt.latest_checkpoint(path_or_dir)
            if found is None:
                raise FileNotFoundError(
                    "no loadable checkpoint under %r" % (path_or_dir,))
            _, rec = found
        elif str(path_or_dir).endswith(_ckpt.MANIFEST_SUFFIX):
            return self._restore_sharded(
                _ckpt.load_sharded_checkpoint(path_or_dir))
        else:
            rec = _ckpt.load_checkpoint(path_or_dir)
        if self._plan is not None:
            return self._restore_mesh(rec)
        payload = rec["payload"]
        if not self._ready:
            dshape, ddt = payload["setup_desc"]["data"]
            lshape, ldt = payload["setup_desc"]["label"]
            self._setup(NDArray(jnp.zeros(dshape, np.dtype(ddt))),
                        NDArray(jnp.zeros(lshape, np.dtype(ldt))))
        # name mapping: gluon gensyms block names per process (dense0,
        # dense1, ...), so the same architecture rebuilt in one process
        # gets shifted names.  Exact names map directly; otherwise map
        # positionally (collect_params order is construction order) with
        # a per-param shape check — a genuinely different model fails.
        mapping = self._map_checkpoint_params(payload["params"])
        groups_ckpt = [[mapping[n] for n in g] for g in payload["groups"]]
        if groups_ckpt != [list(g) for g in self._groups]:
            raise RuntimeError(
                "checkpoint was taken from a trainer with different "
                "parameter groups (optimizer/grouping mismatch): %r vs %r"
                % (groups_ckpt, self._groups))
        for cn, enc in payload["params"].items():
            name = mapping[cn]
            p = self._params_by_name[name]
            p._data._set_data(jax.device_put(
                jnp.asarray(_ckpt.decode_array(enc)),
                self._param_shardings[name]))
        new_states = []
        for gi, (raw, encs) in enumerate(zip(self._states_raw,
                                             payload["states"])):
            leaves, treedef = jax.tree_util.tree_flatten(raw)
            if len(leaves) != len(encs):
                raise RuntimeError(
                    "optimizer state leaf count mismatch for group %d "
                    "(%d vs %d): different optimizer?"
                    % (gi, len(leaves), len(encs)))
            sh = self._group_shardings[gi]
            vals = [jax.device_put(jnp.asarray(_ckpt.decode_array(e)), sh)
                    for e in encs]
            new_states.append(jax.tree_util.tree_unflatten(treedef, vals))
        self._states_raw = new_states
        if self._reduced and "loss_scale" in payload:
            ls = payload["loss_scale"]
            self._ls_scale = jnp.asarray(ls["scale"], jnp.float32)
            self._ls_good = jnp.asarray(ls["good_steps"], jnp.int32)
            self._ls_skipped = jnp.asarray(ls["skipped"], jnp.int32)
            self._ls_reported_skipped = int(ls["skipped"])
        self._step_count = int(payload["step_count"])
        self._opt.num_update = self._step_count
        _rng.set_state(payload["rng"])
        np.random.set_state(payload["numpy_global"])
        self._inflight.clear()
        return dict(payload["cursor"], step=self._step_count)

    def fit(self, train_data, num_epoch=1, eval_metric="loss",
            batch_end_callback=None, epoch_end_callback=None,
            prefetch_depth=2, bulk_size=None, logger=None,
            checkpoint_dir=None, checkpoint_every=None, resume=False,
            checkpoint_keep=3, metrics_path=None):
        """Overlapped training loop over a ``DataIter``: device prefetch +
        run-ahead dispatch + lazy metrics — the three stages of the step
        pipelined (reference: the engine keeps ``model.py:157``'s loop
        async; here ``PrefetchToDeviceIter`` ships batch *k+1* while step
        *k* executes and the metric accumulates device-resident).

        ``train_data`` yielding host batches is wrapped in a
        ``PrefetchToDeviceIter`` targeting ``batch_sharding`` so ``step``'s
        fast path reuses the prefetched transfer; an iterator that is
        already a ``DeviceFeedIter`` is consumed as-is.  ``bulk_size``
        scopes ``engine.bulk`` around each epoch (None keeps the global
        window).  The loss is accumulated via ``EvalMetric.update_lazy`` —
        no per-step host fetch; callbacks that read the metric
        (``Speedometer``) fetch at their own flush boundaries.

        Fault tolerance (``docs/resilience.md``): with ``checkpoint_dir``
        set, the full training state (params + optimizer state + RNG +
        epoch/batch cursor) is snapshotted atomically every
        ``checkpoint_every`` steps (default ``DEFAULT_CHECKPOINT_EVERY``)
        and at each epoch end; ``resume=True`` restores the newest
        loadable checkpoint and continues from its cursor — with a
        deterministic iterator the post-crash run converges
        bitwise-identically to the uncrashed one.  Snapshots are taken
        after an explicit flush, so a crash mid-``bulk()`` window never
        checkpoints run-ahead state.

        Observability (docs/observability.md): ``metrics_path`` writes a
        versioned telemetry-metrics JSON at the end of training (also
        written automatically under the telemetry directory when
        ``mx.telemetry.enable(dir)`` is armed); ``tools/parse_log.py``
        reads it back.  Returns the metric."""
        import logging

        from .. import metric as _metric
        from ..io import DeviceFeedIter, PrefetchToDeviceIter
        from ..module.base_module import BatchEndParam, _as_list

        log = logger or logging
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        if checkpoint_dir and checkpoint_every is None:
            checkpoint_every = DEFAULT_CHECKPOINT_EVERY
        start_epoch, skip_batches = 0, 0
        if checkpoint_dir and resume:
            from ..resilience import checkpoint as _ckpt
            if (_ckpt.latest_sharded_checkpoint(checkpoint_dir)
                    if (self._zero and self._plan is None) else
                    _ckpt.latest_checkpoint(checkpoint_dir)) is not None:
                cursor = self.restore_checkpoint(checkpoint_dir)
                if cursor.get("epoch") is not None:
                    start_epoch = int(cursor["epoch"])
                    nb = cursor.get("nbatch")
                    skip_batches = (int(nb) + 1) if nb is not None else 0
                log.info("resumed from %s at step %d (epoch %d, skipping "
                         "%d replayed batches)", checkpoint_dir,
                         self._step_count, start_epoch, skip_batches)
        it = train_data
        if not isinstance(it, DeviceFeedIter):
            it = PrefetchToDeviceIter(train_data, sharding=self.batch_sharding,
                                      depth=prefetch_depth)
        for epoch in range(start_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            if epoch > start_epoch:
                it.reset()
            with engine_mod.bulk(bulk_size or engine_mod.bulk_size()):
                batches = iter(it)
                nbatch = -1
                while True:
                    # input wait: time the loop blocks on the feed — the
                    # doctor's input_wait phase (a slow pipeline shows up
                    # HERE, not inside step()).  One bool check when
                    # telemetry is off.
                    tele_on = _tele._ENABLED
                    t_in = time.perf_counter() if tele_on else 0.0
                    try:
                        batch = next(batches)
                    except StopIteration:
                        break
                    if tele_on:
                        _tele.attribution().add_phase(
                            "input_wait", time.perf_counter() - t_in)
                    nbatch += 1
                    if epoch == start_epoch and nbatch < skip_batches:
                        # replayed batch: consumed (keeps any iterator
                        # RNG in phase) but already trained pre-crash
                        continue
                    loss = self.step(batch.data[0], batch.label[0])
                    t_m = time.perf_counter() if tele_on else 0.0
                    eval_metric.update_lazy(batch.label, [loss])
                    if batch_end_callback is not None:
                        params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                               eval_metric=eval_metric,
                                               locals=None)
                        for cb in _as_list(batch_end_callback):
                            cb(params)
                    if tele_on:
                        # metric updates + callback fetches (Speedometer
                        # drains the lazy metric at its own boundaries)
                        _tele.attribution().add_phase(
                            "metric_drain", time.perf_counter() - t_m)
                    if checkpoint_dir and checkpoint_every and \
                            self._step_count % checkpoint_every == 0:
                        self.save_checkpoint(checkpoint_dir, epoch=epoch,
                                             nbatch=nbatch,
                                             keep=checkpoint_keep)
            # bulk exit flushed the ring: everything below sees finished
            # steps, so the epoch log's fetch is the window's ONE sync
            for name, val in eval_metric.get_name_value():
                log.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            log.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)
            if checkpoint_dir and self._ready:
                # epoch boundary: cursor points at the NEXT epoch's start
                self.save_checkpoint(checkpoint_dir, epoch=epoch + 1,
                                     nbatch=None, keep=checkpoint_keep)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, None, None, None)
        self._dump_metrics(metrics_path, log)
        return eval_metric

    def _dump_metrics(self, metrics_path, log):
        """Versioned metrics JSON at the end of ``fit``: the registry
        scrape (pipeline/dispatch gauges registered by PipelineStats,
        anything else armed in-process) written to ``metrics_path``, or
        — when telemetry is armed with a directory — to
        ``<dir>/metrics-<role><rank>-<pid>.json``.  The document
        ``tools/parse_log.py`` reads (``telemetry.SCHEMA_VERSION``)."""
        import os as _os
        path = metrics_path
        if path is None and _tele.enabled() and _tele.telemetry_dir():
            rank = _tele.rank()
            path = _os.path.join(
                _tele.telemetry_dir(),
                "metrics-worker%s-%d.json"
                % ("" if rank is None else rank, _os.getpid()))
        if not path:
            return
        try:
            attr = _tele.attribution()
            # close the open attribution window first: the run's tail
            # steps (and the partial flight window) must reach both the
            # dump and the ring before the process exits
            attr.flush_window()
            _tele.dump_metrics(path, source="trainer.fit", extra={
                "step_count": self._step_count,
                "dispatch_stats": self.dispatch_stats.snapshot(),
                "attribution": attr.snapshot()})
        except OSError:
            log.exception("metrics dump to %s failed", path)

    def _dist_step(self, train_vals, aux_vals, x, y, rng, lr_host):
        """Split step for multi-process data parallelism: local grads ->
        kvstore push/pull (summed across workers by the PS sync round) ->
        average -> donated optimizer update.  Averaging the per-worker
        mean-loss gradients reproduces the single-process full-batch
        gradient exactly (equal shards), so N workers with batch B/N match
        one process with batch B to float tolerance — the property
        tests/test_dist.py asserts (reference: tests/nightly/dist_lenet.py)."""
        if self._grad_fn is None:
            self._grad_fn = self._build_grad_step()
            self._update_fn = self._build_update_step()
        tele_on = _tele._ENABLED
        attr = _tele.attribution() if tele_on else None
        t0 = time.perf_counter() if tele_on else 0.0
        flat, muts = self._grad_fn(train_vals, aux_vals, x, y, rng)
        if tele_on:
            t1 = time.perf_counter()
            attr.add_phase("dispatch", t1 - t0)
        self._kv.push(self._flat_key, NDArray(flat))
        self._kv.pull(self._flat_key, out=self._flat_out)
        if tele_on:
            t2 = time.perf_counter()
            attr.add_phase("collective_or_ps", t2 - t1)
        # global-batch mean loss comes back out of the update jit, so
        # every rank's callbacks see the number the single-process run
        # would (a local loss would diverge across ranks)
        loss_val, new_vals, new_states = self._update_fn(
            train_vals, tuple(self._states_raw), self._flat_out._data,
            jnp.float32(lr_host), jnp.int32(self._step_count))
        if tele_on:
            attr.add_phase("dispatch", time.perf_counter() - t2)
        return loss_val, new_vals, new_states, muts

    def set_learning_rate(self, lr):
        self._opt.set_learning_rate(lr)
