"""Image loading + augmentation pipeline (host-side).

Reference: ``python/mxnet/image/image.py`` (ImageIter + augmenters) and the
C++ ``ImageRecordIter`` (``src/io/iter_image_recordio_2.cc``, default
augmenters ``src/io/image_aug_default.cc``).

TPU-first design note: the reference augments into device NDArrays because
its CPU context is host memory; here augmentation stays in *numpy* on the
host worker (cv2 kernels, no per-image device dispatch) and the batch is
shipped to HBM once — jax's async dispatch overlaps the transfer with TPU
compute, replacing the reference's pinned-memory PrefetcherIter.
Augmenter call signature (NDArray in/out) is preserved at the API boundary.
"""
from __future__ import annotations

import logging
import os
import random

import numpy as np

from .. import io as _io
from .. import ndarray as nd
from .. import recordio
from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["imread", "imdecode", "imresize", "scale_down", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "Augmenter", "SequentialAug", "ResizeAug",
           "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "ColorNormalizeAug", "RandomGrayAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter"]


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError as e:
        raise ImportError("image ops require OpenCV (cv2)") from e


def _as_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return np.asarray(img)


def _out(arr, ref, dtype=None):
    """numpy-in -> numpy-out (host pipeline stays on host: zero per-image
    device dispatch); NDArray-in -> NDArray-out (reference API parity)."""
    if dtype is not None:
        arr = np.ascontiguousarray(arr, dtype=dtype)
    if isinstance(ref, NDArray):
        return nd.array(arr, dtype=arr.dtype)
    return arr


def imdecode(buf, to_rgb=True, flag=1, **kwargs):
    """Decode an image byte buffer to HWC (RGB by default) NDArray
    (reference: image.py imdecode via cv2)."""
    cv2 = _cv2()
    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if img is None:
        raise MXNetError("cannot decode image")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img, dtype=np.uint8)


def imread(filename, to_rgb=True, flag=1):
    with open(filename, "rb") as f:
        return imdecode(f.read(), to_rgb=to_rgb, flag=flag)


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    return _out(cv2.resize(_as_np(src), (w, h), interpolation=interp), src)


def scale_down(src_size, size):
    """Scale (w, h) down to fit src_size (reference: image.py scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals `size`."""
    cv2 = _cv2()
    img = _as_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return _out(cv2.resize(img, (new_w, new_h), interpolation=interp), src)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    img = _as_np(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        cv2 = _cv2()
        out = cv2.resize(out, size, interpolation=interp)
    return _out(out, src)


def random_crop(src, size, interp=2):
    img = _as_np(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    img = _as_np(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    img = _as_np(src).astype(np.float32)
    img = img - np.asarray(mean, dtype=np.float32)
    if std is not None:
        img = img / np.asarray(std, dtype=np.float32)
    return _out(img, src)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop with area/aspect jitter (Inception-style)."""
    img = _as_np(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = random.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(random.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


# ---------------------------------------------------------------------------
# augmenters
# ---------------------------------------------------------------------------
class Augmenter:
    """Image augmenter base (reference: image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        cv2 = _cv2()
        return _out(cv2.resize(_as_np(src), tuple(self.size),
                               interpolation=self.interp), src)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            img = _as_np(src)
            return _out(np.ascontiguousarray(img[:, ::-1]), src)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _out(_as_np(src).astype(self.typ), src)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return _out(_as_np(src).astype(np.float32) * alpha, src)


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _as_np(src).astype(np.float32)
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        gray = (img * self._coef).sum(axis=2, keepdims=True).mean()
        return _out(img * alpha + gray * (1 - alpha), src)


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _as_np(src).astype(np.float32)
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return _out(img * alpha + gray * (1 - alpha), src)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], dtype=np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], dtype=np.float32)

    def __call__(self, src):
        img = _as_np(src).astype(np.float32)
        alpha = random.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      dtype=np.float32)
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        return _out(np.dot(img, t), src)


class ColorJitterAug(SequentialAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        random.shuffle(ts)
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting noise (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, dtype=np.float32)
        self.eigvec = np.asarray(eigvec, dtype=np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(np.float32)
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return _out(_as_np(src).astype(np.float32) + rgb, src)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = np.asarray(mean, dtype=np.float32) \
            if mean is not None else None
        self.std = np.asarray(std, dtype=np.float32) \
            if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = np.array([[0.21, 0.21, 0.21],
                     [0.72, 0.72, 0.72],
                     [0.07, 0.07, 0.07]], dtype=np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            return _out(np.dot(_as_np(src).astype(np.float32), self._mat), src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference: image.py CreateAugmenter
    — mirrors the C++ default augmenter chain, image_aug_default.cc)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and (not isinstance(mean, np.ndarray) or mean.shape[0] in (1, 3)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Image iterator reading .rec files or image lists with augmentation
    (reference: image.py ImageIter ≈ the C++ ImageRecordIter).

    Supports distributed sharding via num_parts/part_index (the reference
    shards the RecordIO file by worker rank)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", layout="NCHW",
                 preprocess_threads=0, **kwargs):
        super().__init__(batch_size)
        # decode-thread count for the native libjpeg pipeline (reference:
        # preprocess_threads on ImageRecordIter, iter_image_recordio_2.cc
        # OMP team); 0 = all host cores
        self.preprocess_threads = int(preprocess_threads)
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        # TPU-native extension: layout="NHWC" emits batches exactly as the
        # decoder produces them (HWC) — no host-side transpose, and uint8
        # dtype keeps the host->device transfer 4x narrower; normalization
        # then fuses on-device
        assert layout in ("NCHW", "NHWC")
        self.layout = layout

        self.imgrec = None
        self.imglist = None
        self.seq = None
        self._offsets = None

        if path_imgrec:
            idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.isfile(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                         "r")
                self.seq = list(self.imgrec.keys)
            else:
                # no .idx sidecar: build an in-memory offset index with one
                # sequential scan so shuffle / num_parts sharding still work
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self._offsets = []
                while True:
                    pos = self.imgrec.tell()
                    if self.imgrec.read() is None:
                        break
                    self._offsets.append(pos)
                self.imgrec.reset()
                self.seq = list(range(len(self._offsets)))
        elif path_imglist or imglist is not None:
            if path_imglist:
                imglist_dict = {}
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = np.array(parts[1:-1], dtype=np.float32)
                        imglist_dict[int(parts[0])] = (label, parts[-1])
            else:
                imglist_dict = {}
                for i, item in enumerate(imglist):
                    imglist_dict[i] = (np.array(item[:-1], dtype=np.float32),
                                       item[-1])
            self.imglist = imglist_dict
            self.path_root = path_root
            self.seq = list(imglist_dict.keys())
        else:
            raise ValueError("need path_imgrec, path_imglist or imglist")

        # distributed sharding (reference: kv.num_workers/rank split)
        if num_parts > 1 and self.seq is not None:
            n_per = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n_per:(part_index + 1) * n_per]

        if aug_list is None:
            aug_list = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast", "saturation",
                         "hue", "pca_noise", "rand_gray", "inter_method")})
        self.auglist = aug_list

        # native fast path: when the spatial part of the chain is
        # deterministic resize+center-crop, the C++ runtime decodes the
        # whole batch in parallel (native/mxtpu_io.cc — the
        # ImageRecordIOParser2 analogue); remaining per-pixel augs
        # (cast/normalize) apply batched
        self._native_resize = 0
        self._native_tail = None
        if kwargs.get("native_decode", True):
            spatial, tail = [], []
            for aug in aug_list:
                if isinstance(aug, (ResizeAug, CenterCropAug)):
                    spatial.append(aug)
                else:
                    tail.append(aug)
            resize = next((a.size for a in spatial
                           if isinstance(a, ResizeAug)), 0)
            # the native pipeline is resize-short (optional) + center-crop
            # to data_shape — exactly ResizeAug/CenterCropAug semantics, so
            # engage whenever the spatial chain is those two (in any
            # combination, including none) and every crop targets data_shape
            target = (data_shape[2], data_shape[1])
            crops_ok = all(a.size == target for a in spatial
                           if isinstance(a, CenterCropAug))
            if crops_ok and \
                    all(isinstance(a, (CastAug, ColorNormalizeAug))
                        for a in tail):
                from .. import _native
                if _native.available():
                    self._native_resize = resize
                    self._native_tail = tail

        c, h, w = self.data_shape
        dshape = (batch_size, h, w, c) if layout == "NHWC" \
            else (batch_size,) + self.data_shape
        self.provide_data = [_io.DataDesc(data_name, dshape, np.dtype(dtype),
                                          layout=layout)]
        if label_width > 1:
            self.provide_label = [_io.DataDesc(label_name,
                                               (batch_size, label_width))]
        else:
            self.provide_label = [_io.DataDesc(label_name, (batch_size,))]
        self.cur = 0
        self._allow_read = True
        self.last_batch_handle = last_batch_handle
        self._cache_data = None
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            random.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """Read one (label, image-bytes) sample."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                if getattr(self, "_offsets", None) is not None:
                    s = self.imgrec.read_at(self._offsets[idx])
                else:
                    s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _decode_one(self, s):
        """cv2-decode one record payload through the full augmenter chain."""
        decode_flag = 1 if self.data_shape[0] == 3 else 0
        img = _cv2().imdecode(np.frombuffer(s, dtype=np.uint8), decode_flag)
        if img is None:
            raise MXNetError("cannot decode image record")
        if decode_flag == 1:
            img = _cv2().cvtColor(img, _cv2().COLOR_BGR2RGB)
        for aug in self.auglist:
            img = _as_np(aug(img))
        if img.ndim == 2:
            img = img[:, :, None]
        return img

    def next(self):
        data, label, pad = self.next_numpy()
        d = nd.array(data, dtype=self.dtype)
        lab = nd.array(label if self.label_width > 1 else label[:, 0])
        return _io.DataBatch([d], [lab], pad=pad)

    def next_numpy(self):
        """One batch as ``(data, label, pad)`` *numpy* arrays — the host
        side of ``next()`` with no device arrays created.  The
        multi-process pipeline workers (io/pipeline.py) call this so a
        worker can never initialise a jax backend; ``label`` always has
        shape (B, label_width)."""
        if self._native_tail is not None:
            return self._next_native_numpy()
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, h, w, c), dtype=np.float32)
        lw = self.label_width
        batch_label = np.zeros((self.batch_size, lw), dtype=np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, s = self.next_sample()
                batch_data[i] = self._decode_one(s)
                batch_label[i] = np.asarray(label, np.float32).reshape(-1)[:lw]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        if pad:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "keep":
                # emit the partial tail as-is (C++ round_batch=0)
                batch_data = batch_data[:i]
                batch_label = batch_label[:i]
                pad = 0
            else:
                # pad by repeating the last valid sample (reference C++
                # iterator); DataBatch.pad tells consumers how many to drop
                batch_data[i:] = batch_data[i - 1]
                batch_label[i:] = batch_label[i - 1]
        if self.layout != "NHWC":
            batch_data = batch_data.transpose(0, 3, 1, 2)
        return (batch_data.astype(self.dtype, copy=False), batch_label, pad)

    def _next_native_numpy(self):
        """Batch decode through the C++ runtime (deterministic pipelines)."""
        from .. import _native
        c, h, w = self.data_shape
        lw = self.label_width
        bufs, labels = [], []
        try:
            while len(bufs) < self.batch_size:
                label, s = self.next_sample()
                bufs.append(bytes(s))
                labels.append(np.asarray(label, np.float32).reshape(-1)[:lw])
        except StopIteration:
            if not bufs:
                raise
        pad = self.batch_size - len(bufs)
        if pad:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle != "keep":
                bufs.extend([bufs[-1]] * pad)
                labels.extend([labels[-1]] * pad)
                # keep pad count; 'keep' emits partial
        if not all(b[:2] == b"\xff\xd8" for b in bufs):
            # non-JPEG records (e.g. PNG-packed .rec): libjpeg can't decode
            # them — permanently fall back to the cv2 python path
            self._native_tail = None
            return self._decode_python_bufs(bufs, labels, pad)
        decoded, fails = _native.decode_batch(
            bufs, h, w, c, resize_short=self._native_resize,
            num_threads=self.preprocess_threads)
        if fails:
            raise MXNetError("%d corrupt image records in batch" % fails)
        if np.dtype(self.dtype) == np.uint8 and not any(
                isinstance(a, ColorNormalizeAug) for a in self._native_tail):
            batch = decoded           # raw uint8 pass-through, no host copy
        else:
            batch = decoded.astype(np.float32)
            for aug in self._native_tail:
                if isinstance(aug, ColorNormalizeAug):
                    if aug.mean is not None:
                        batch = batch - aug.mean
                    if aug.std is not None:
                        batch = batch / aug.std
                elif isinstance(aug, CastAug):
                    batch = batch.astype(aug.typ)
        if self.layout != "NHWC":
            batch = batch.transpose(0, 3, 1, 2)
        lab = np.stack(labels).reshape(-1, lw)
        return (batch.astype(self.dtype, copy=False), lab,
                0 if self.last_batch_handle == "keep" else pad)

    def _decode_python_bufs(self, bufs, labels, pad):
        """cv2-decode pre-collected record buffers (fallback from the
        native path)."""
        lw = self.label_width
        batch = np.stack([self._decode_one(s) for s in bufs]) \
            .astype(np.float32)
        if self.layout != "NHWC":
            batch = batch.transpose(0, 3, 1, 2)
        lab = np.stack(labels).reshape(-1, lw)
        return (batch.astype(self.dtype, copy=False), lab,
                0 if self.last_batch_handle == "keep" else pad)
