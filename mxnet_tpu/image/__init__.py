"""`mx.image` namespace (reference: python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from .image import ImageIter  # noqa: F401
