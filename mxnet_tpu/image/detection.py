"""Detection image pipeline: ImageDetIter + detection augmenters.

Reference: ``python/mxnet/image/detection.py`` (ImageDetIter, DetAugmenter
family) and the C++ ``ImageDetRecordIter`` (``src/io/
iter_image_det_recordio.cc`` + ``image_det_aug_default.cc``).

Detection labels ride the record header as a flat vector:
``[header_width, object_width, (extra...), obj0..., obj1..., ...]`` with
each object ``[id, xmin, ymin, xmax, ymax, (extra...)]`` in normalized
coordinates.  Augmenters transform image and boxes together; batches pad
the per-image object list with -1 rows to a fixed label shape, exactly the
contract MultiBoxTarget consumes.
"""
from __future__ import annotations

import random

import numpy as np

from .. import io as _io
from .. import ndarray as nd
from ..base import MXNetError
from .image import (ImageIter, ResizeAug, ForceResizeAug, CastAug,
                    ColorNormalizeAug, Augmenter, fixed_crop)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomSelectAug", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter: __call__(src, label) -> (src, label)
    (reference: detection.py DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Borrow a plain image augmenter (labels pass through) — valid only
    for geometry-preserving augs (color jitter, cast, normalize)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Random horizontal flip mirroring the boxes (reference:
    detection.py DetHorizontalFlipAug / image_det_aug_default.cc)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            tmp = 1.0 - label[valid, 1]
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = tmp
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop with object-coverage constraints (reference:
    detection.py DetRandomCropAug; SSD-style sampling)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[:2]
        crop = self._propose(label)
        if crop is None:
            return src, label
        x0, y0, cw, ch = crop
        new_label = self._update_labels(label, (x0, y0, cw, ch))
        if new_label is None:
            return src, label
        out = fixed_crop(src, int(x0 * w), int(y0 * h),
                         max(1, int(cw * w)), max(1, int(ch * h)))
        return out, new_label

    def _propose(self, label):
        valid = label[label[:, 0] >= 0]
        for _ in range(self.max_attempts):
            area = random.uniform(*self.area_range)
            ratio = random.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(area * ratio))
            ch = min(1.0, np.sqrt(area / ratio))
            x0 = random.uniform(0, 1 - cw)
            y0 = random.uniform(0, 1 - ch)
            if len(valid) == 0:
                return (x0, y0, cw, ch)
            # coverage of each object by the crop
            ix0 = np.maximum(valid[:, 1], x0)
            iy0 = np.maximum(valid[:, 2], y0)
            ix1 = np.minimum(valid[:, 3], x0 + cw)
            iy1 = np.minimum(valid[:, 4], y0 + ch)
            iw = np.clip(ix1 - ix0, 0, None)
            ih = np.clip(iy1 - iy0, 0, None)
            inter = iw * ih
            obj_area = (valid[:, 3] - valid[:, 1]) * \
                (valid[:, 4] - valid[:, 2])
            cover = inter / np.maximum(obj_area, 1e-12)
            if (cover >= self.min_object_covered).any():
                return (x0, y0, cw, ch)
        return None

    def _update_labels(self, label, crop):
        x0, y0, cw, ch = crop
        out = label.copy()
        kept = 0
        for i in range(out.shape[0]):
            if out[i, 0] < 0:
                continue
            bx0 = max(out[i, 1], x0)
            by0 = max(out[i, 2], y0)
            bx1 = min(out[i, 3], x0 + cw)
            by1 = min(out[i, 4], y0 + ch)
            inter = max(0.0, bx1 - bx0) * max(0.0, by1 - by0)
            area = (out[i, 3] - out[i, 1]) * (out[i, 4] - out[i, 2])
            if area <= 0 or inter / area < self.min_eject_coverage:
                out[i, 0] = -1.0   # ejected
                continue
            out[i, 1] = (bx0 - x0) / cw
            out[i, 2] = (by0 - y0) / ch
            out[i, 3] = (bx1 - x0) / cw
            out[i, 4] = (by1 - y0) / ch
            kept += 1
        return out if kept else None


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter from a list (or skip)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if random.random() < self.skip_prob or not self.aug_list:
            return src, label
        return random.choice(self.aug_list)(src, label)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_pad=0.0,
                       rand_gray=0.0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Build the standard detection augmenter chain (reference:
    detection.py CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                area_range, min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force to the network input size after geometric augs
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator over .rec/.lst sources (reference:
    detection.py ImageDetIter ≈ C++ ImageDetRecordIter).

    Emits data (B, C, H, W) and label (B, max_objects, label_width) with
    -1-padded object rows."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_mirror", "mean", "std",
                         "min_object_covered", "area_range",
                         "aspect_ratio_range", "min_eject_coverage",
                         "max_attempts")})
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name,
                         last_batch_handle=last_batch_handle,
                         native_decode=False)
        self.det_auglist = aug_list
        self._label_shape = self._estimate_label_shape()
        self.provide_label = [_io.DataDesc(
            label_name, (batch_size,) + self._label_shape)]

    # -- label plumbing ----------------------------------------------------
    def _parse_label(self, raw):
        """Flat header vector -> (num_obj, obj_width) array (reference:
        ImageDetIter._parse_label)."""
        raw = np.asarray(raw, np.float32).reshape(-1)
        if raw.size < 2:
            raise MXNetError("invalid detection label: too short")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise MXNetError("invalid detection label: object width < 5")
        body = raw[header_width:]
        if body.size % obj_width != 0:
            raise MXNetError("invalid detection label length")
        return body.reshape(-1, obj_width)

    def _estimate_label_shape(self):
        max_count, width = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self.next_sample()
                parsed = self._parse_label(label)
                max_count = max(max_count, parsed.shape[0])
                width = max(width, parsed.shape[1])
        except StopIteration:
            pass
        self.reset()
        return (max(1, max_count), width)

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.data_shape = tuple(data_shape)
            self.provide_data = [_io.DataDesc(
                self.provide_data[0].name,
                (self.batch_size,) + self.data_shape)]
        if label_shape is not None:
            self._label_shape = tuple(label_shape)
            self.provide_label = [_io.DataDesc(
                self.provide_label[0].name,
                (self.batch_size,) + self._label_shape)]

    def augmentation_transform(self, data, label):
        for aug in self.det_auglist:
            data, label = aug(data, label)
        return data, label

    def next(self):
        c, h, w = self.data_shape
        n_obj, lw = self._label_shape
        batch_data = np.zeros((self.batch_size, h, w, c), np.float32)
        batch_label = np.full((self.batch_size, n_obj, lw), -1.0, np.float32)
        i = 0
        try:
            while i < self.batch_size:
                raw_label, s = self.next_sample()
                img = self._decode_raw(s)
                label = self._parse_label(raw_label)
                img, label = self.augmentation_transform(img, label)
                batch_data[i] = np.asarray(img, np.float32)
                k = min(label.shape[0], n_obj)
                batch_label[i, :k, :label.shape[1]] = label[:k]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        if pad:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "keep":
                batch_data = batch_data[:i]
                batch_label = batch_label[:i]
                pad = 0
            else:
                batch_data[i:] = batch_data[i - 1]
                batch_label[i:] = batch_label[i - 1]
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        return _io.DataBatch([data], [nd.array(batch_label)], pad=pad)

    def _decode_raw(self, s):
        from .image import imdecode
        return imdecode(s).asnumpy()
