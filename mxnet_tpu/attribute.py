"""`mx.attribute` (reference: python/mxnet/attribute.py) — AttrScope for
scoped symbol attributes (ctx_group / __layout__ etc.)."""
from .symbol.symbol import AttrScope

__all__ = ["AttrScope"]
