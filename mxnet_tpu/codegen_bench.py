"""Host-only codegen-tier bench (the r05 subprocess pattern).

Run as ``python -m mxnet_tpu.codegen_bench`` under ``JAX_PLATFORMS=cpu``
(bench.py's ``codegen`` stage does, BEFORE backend acquisition, so the
keys stay live when the TPU is down).  Emits one JSON line:

- ``codegen_generated_speedup_host``: REAL measured wall-time ratio of
  the unfused chain execution (op-at-a-time over the mined tape eqns —
  every intermediate materializes, one dispatch per op: exactly the
  semantics the fusion pass prices as "unfused") vs the generated
  Pallas kernel (``ops/generated_kernels.py``, interpret on the host —
  one pass, one dispatch), summed over every shipped generated kernel.
  Gated ``higher`` in tools/bench_compare.py from its first two live
  rounds.
- ``codegen_modeled_bytes_saved_pct``: the deterministic modeled win of
  the shipped chains — ``sum(bytes_saved) / sum(unfused_bytes)`` over
  the mxgen lowering (``analysis/codegen.py``), the same numbers the
  ``codegen_chains`` STATIC_BUDGETS.json rows pin.
- ``codegen_numerics_ok``: 1.0 iff every registered generated kernel
  passes its host auto-equivalence check AND the real
  ``pl.pallas_call`` interpret path matches the tape reference within
  EQUIV_TOL (1e-5) AND the pallas path bitwise-repeats — gated at zero
  slack.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BENCH_REPS = 20       # timing samples per arm (median)


def _bench(fn, reps=BENCH_REPS):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    import jax

    from mxnet_tpu.analysis import codegen as cg
    from mxnet_tpu.ops import generated_kernels as gen

    out = {}
    kernels = gen.build_shipped_generated()
    lowered = {lk.name: lk for lk in cg.shipped_lowered()}

    # modeled (deterministic, device-free): the mxgen lowering's own
    # byte contract — what the codegen_chains budget rows pin
    unfused = sum(lk.unfused_bytes for lk in lowered.values())
    saved = sum(lk.bytes_saved for lk in lowered.values())
    out["codegen_modeled_bytes_saved_pct"] = round(
        100.0 * saved / unfused, 2) if unfused else 0.0

    # measured + numerics, per shipped kernel
    t_unfused_total, t_fused_total = 0.0, 0.0
    numerics_ok = True
    max_err = 0.0
    for gk in kernels:
        lk = lowered[gk.name]
        inputs = cg.seeded_inputs(lk.in_avals, cg.EQUIV_SEED)
        ref = cg.reference_outputs(lk, inputs)
        dev_inputs = [jax.device_put(x) for x in inputs]

        def run_unfused(lk=lk, xs=dev_inputs):
            # op-at-a-time: each tape eqn dispatches and materializes
            # separately — the unfused spelling the chain replaces
            outs = cg.reference_outputs(lk, xs)
            jax.block_until_ready(outs)
            return outs

        fused = jax.jit(lambda *xs, gk=gk: tuple(
            gen.generated_call(gk, *xs, interpret=True)))

        got = fused(*dev_inputs)          # warm (compile)
        jax.block_until_ready(got)
        run_unfused()

        # numerics: pallas interpret vs the tape reference, and the
        # pallas path must bitwise-repeat
        for r, g, aval in zip(ref, got, lk.out_avals):
            r, g = np.asarray(r), np.asarray(g)
            if np.issubdtype(r.dtype, np.floating):
                err = float(np.max(np.abs(r.astype("f8") - g.astype("f8")))) \
                    if r.size else 0.0
                max_err = max(max_err, err)
                if not np.allclose(r, g, rtol=cg.EQUIV_TOL,
                                   atol=cg.EQUIV_TOL):
                    numerics_ok = False
            elif not (r == g).all():
                numerics_ok = False
        got2 = fused(*dev_inputs)
        jax.block_until_ready(got2)
        if not all((np.asarray(a) == np.asarray(b)).all()
                   for a, b in zip(got, got2)):
            numerics_ok = False
        if not gk.equivalence_ok:
            numerics_ok = False

        t_unfused_total += _bench(run_unfused)
        t_fused_total += _bench(
            lambda fused=fused, xs=dev_inputs:
            jax.block_until_ready(fused(*xs)))

    out["codegen_n_kernels"] = len(kernels)
    out["codegen_unfused_ms"] = round(t_unfused_total * 1e3, 4)
    out["codegen_fused_ms"] = round(t_fused_total * 1e3, 4)
    out["codegen_generated_speedup_host"] = round(
        t_unfused_total / t_fused_total, 3) if t_fused_total else 0.0
    out["codegen_numerics_max_err"] = float(max_err)
    out["codegen_numerics_ok"] = 1.0 if numerics_ok else 0.0

    print(json.dumps(out))
    return 0 if out["codegen_numerics_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
