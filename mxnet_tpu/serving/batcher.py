"""Batcher: coalesce concurrent single requests into bucketed batches,
with SLO tiers, deadline-aware coalescing, and deterministic load shed.

The dynamic-batching core of the serving layer (the reference analogue is
the server-side request coalescing TF-Serving ships; MXNet's
BucketingModule solved the same compile-explosion problem for training).
Requests carry ``(tier, deadline_ms)``; a priority structure feeds one
worker thread, which takes up to ``max_batch`` requests ordered by
``(tier, deadline, arrival)`` — so under contention the gold tier is
coalesced first and, within a tier, near-deadline requests are preferred
into the next bucket — stacks them, and hands the batch to the
:class:`~mxnet_tpu.serving.runner.ModelRunner`, which pads to the nearest
bucket.  Results are split back per-request.

Overload answers, in order of preference (the anti-queue-collapse
contract, ROADMAP item 3):

- **shed before rot**: when the *modeled* queue wait (queued position /
  ``max_batch`` x the measured-or-hinted per-batch service time) already
  exceeds a request's ``deadline_ms``, the request is refused at
  admission with :class:`RequestShed` carrying a ``retry_after_s`` hint —
  immediately and deterministically, instead of timing out in the queue.
  The worker re-runs the same arithmetic before each batch and sheds
  queued requests that have become hopeless (``shed_at="sweep"``).
  Because lower tiers sort behind higher ones, their modeled wait grows
  first and shedding is confined to the lowest tier until it is empty.
- **evict, lowest tier first**: a submit against a full queue evicts the
  worst-ranked queued request when the newcomer strictly outranks it
  (deterministic: lowest tier, then latest deadline, then newest);
  otherwise the newcomer gets :class:`ServerBusy` (HTTP 429).
- ``drain()`` stops admission, completes everything already queued, and
  joins the worker — the graceful-shutdown half of the contract.

``swap_runner()`` replaces the model *under drain of the in-flight batch
only*: it waits for the batch currently executing to finish (the runner
lock), installs the new runner, and every queued request is served by the
replacement — zero in-flight failures, the hot-swap half of the fleet
contract.  All deadline/latency arithmetic uses ``time.monotonic()``
(wall-clock ``time.time()`` would tear under NTP steps).
"""
from __future__ import annotations

import bisect
import math
import threading
import time

import numpy as _np

from ..base import MXNetError
from .stats import ServingStats

__all__ = ["Batcher", "ServerBusy", "Draining", "RequestShed",
           "TIERS", "DEFAULT_TIER", "tier_rank", "tier_name"]

# SLO tiers, best first.  Integer ranks are accepted anywhere a name is
# (0 = gold).  The *names* are what stats and HTTP payloads speak.
TIERS = {"gold": 0, "silver": 1, "bronze": 2}
_TIER_NAMES = {v: k for k, v in TIERS.items()}
DEFAULT_TIER = "gold"


def tier_rank(tier):
    """Canonical integer rank for a tier name or int (0 is best)."""
    if isinstance(tier, bool):
        raise MXNetError("bad tier %r" % (tier,))
    if isinstance(tier, int):
        if tier < 0:
            raise MXNetError("tier rank must be >= 0, got %d" % tier)
        return tier
    try:
        return TIERS[str(tier).lower()]
    except KeyError:
        raise MXNetError("unknown tier %r (want one of %s or an int rank)"
                         % (tier, sorted(TIERS))) from None


def tier_name(rank):
    """Display name for a rank (falls back to ``tier<rank>``)."""
    return _TIER_NAMES.get(int(rank), "tier%d" % int(rank))


class ServerBusy(MXNetError):
    """Queue full and the request outranks nothing — reject now rather
    than stall (HTTP 429)."""


class Draining(MXNetError):
    """Server is draining — no new admissions (HTTP 503)."""


class RequestShed(MXNetError):
    """Request shed by admission control: the modeled queue wait exceeds
    its deadline, or it was evicted by a higher-tier arrival (HTTP 503
    with ``Retry-After`` = ``retry_after_s``)."""

    def __init__(self, message, tier="gold", retry_after_s=1.0,
                 shed_at="admit"):
        super().__init__(message)
        self.tier = tier
        self.retry_after_s = float(retry_after_s)
        self.shed_at = shed_at  # "admit" | "evict" | "sweep"


class _Pending:
    """One in-flight request: a tiny future (stdlib-only) plus its SLO
    coordinates.  Orders by (tier rank, absolute deadline, arrival)."""

    __slots__ = ("example", "_event", "_result", "_exc", "t_submit",
                 "tier_rank", "deadline_ms", "t_deadline", "seq")

    def __init__(self, example, tier_rank=0, deadline_ms=None, seq=0):
        self.example = example
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self.t_submit = time.monotonic()
        self.tier_rank = tier_rank
        self.deadline_ms = deadline_ms
        self.t_deadline = (self.t_submit + deadline_ms / 1000.0
                           if deadline_ms is not None else None)
        self.seq = seq

    @property
    def tier(self):
        return tier_name(self.tier_rank)

    def _key(self):
        return (self.tier_rank,
                self.t_deadline if self.t_deadline is not None
                else float("inf"),
                self.seq)

    def __lt__(self, other):
        return self._key() < other._key()

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_exception(self, exc):
        self._exc = exc
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within %ss" % timeout)
        if self._exc is not None:
            raise self._exc
        return self._result


class Batcher:
    """Deadline-aware dynamic batcher over one :class:`ModelRunner`.

    New-in-fleet parameters (all optional, defaults reproduce the PR-2
    single-tier behavior):

    service_time_hint_ms : pins the modeled per-batch service time used
        by admission control.  Unset, an EWMA of measured batch times is
        used (admission is optimistic until the first measurement).  A
        pinned hint plus a single submitting thread makes every shed
        decision deterministic — what the chaos tests replay.
    on_batch_success / on_batch_error : callbacks fired after each batch
        (the fleet wires its per-model circuit breaker here).
    model : display name carried into stats/errors (fleet routing key).
    """

    def __init__(self, runner, max_batch=None, batch_timeout_ms=2.0,
                 max_queue=256, stats=None, service_time_hint_ms=None,
                 on_batch_success=None, on_batch_error=None, model=None):
        self.runner = runner
        self._max_batch_req = int(max_batch) if max_batch else None
        self.max_batch = min(self._max_batch_req or runner.max_batch,
                             runner.max_batch)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.model = model
        self.stats = stats if stats is not None else \
            ServingStats(runner.buckets)
        self.service_time_hint_ms = service_time_hint_ms
        self.on_batch_success = on_batch_success
        self.on_batch_error = on_batch_error
        self._est_ewma_ms = None
        # _cond guards _heap/_seq and serializes admission against drain
        self._cond = threading.Condition()
        self._heap = []        # sorted by _Pending._key()
        self._seq = 0
        # held while a batch executes on the runner: swap_runner acquires
        # it, so a swap waits exactly for the in-flight batch (hot swap
        # under drain with zero in-flight failures)
        self._runner_lock = threading.Lock()
        self._batch_started = None  # monotonic() while a batch executes
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-batcher", daemon=True)
        self._thread.start()

    # -- admission-control arithmetic --------------------------------------
    @property
    def est_batch_ms(self):
        """Modeled per-batch service time: the pinned hint when set, else
        the EWMA of measured batches (None before any signal)."""
        if self.service_time_hint_ms is not None:
            return float(self.service_time_hint_ms)
        return self._est_ewma_ms

    def _modeled_wait_ms(self, position):
        """Modeled time until a request at 0-based queue ``position`` is
        *served*: full batches ahead of it, plus its own batch, plus the
        batch currently executing (if any), each costing ``est_batch_ms``.
        0.0 when there is no service-time signal yet (admit
        optimistically)."""
        est = self.est_batch_ms
        if est is None:
            return 0.0
        in_flight = 1 if self._batch_started is not None else 0
        return (position // self.max_batch + 1 + in_flight) * est

    def modeled_wait_ms(self):
        """Modeled wait a request submitted *now* at the lowest priority
        would see (the /stats + Retry-After surface)."""
        with self._cond:
            return self._modeled_wait_ms(len(self._heap))

    def stalled(self, threshold_s):
        """True when the in-flight batch has been executing longer than
        ``threshold_s`` — the readiness-probe signal for a wedged runner
        (the process stays live; routing should stop)."""
        started = self._batch_started
        return started is not None and \
            time.monotonic() - started > float(threshold_s)

    # -- client side -------------------------------------------------------
    @property
    def queue_depth(self):
        # len() of a heap mid-sift on another thread can be torn on
        # pypy-likes and is racy in spirit everywhere: read it under
        # the same condition lock submit/sweep mutate it under
        with self._cond:
            return len(self._heap)

    @property
    def draining(self):
        return self._draining.is_set()

    def _retry_after_s(self, wait_ms):
        return max(1.0, math.ceil(wait_ms / 1000.0))

    def submit(self, example, tier=DEFAULT_TIER, deadline_ms=None,
               model=None):
        """Enqueue one example; returns a future-like with ``.result()``.

        ``tier`` orders the request against concurrent load (gold >
        silver > bronze); ``deadline_ms`` arms admission control: when
        the modeled queue wait already exceeds it the request is shed
        *now* (:class:`RequestShed`) instead of timing out queued.
        Raises :class:`ServerBusy` when the queue is full and the request
        outranks nothing, :class:`Draining` after ``drain()`` — never
        blocks the caller."""
        rank = tier_rank(tier)
        if deadline_ms is not None and deadline_ms <= 0:
            raise MXNetError("deadline_ms must be positive, got %r"
                             % (deadline_ms,))
        victim = None
        with self._cond:
            if self._draining.is_set():
                raise Draining("server is draining; request rejected")
            req = _Pending(_np.asarray(example), rank, deadline_ms,
                           self._seq)
            self._seq += 1
            position = bisect.bisect_left(self._heap, req)
            if deadline_ms is not None:
                wait_ms = self._modeled_wait_ms(position)
                if wait_ms > deadline_ms:
                    self.stats.on_shed(req.tier)
                    raise RequestShed(
                        "modeled queue wait %.0fms exceeds deadline %.0fms"
                        " (tier=%s, depth=%d); shed at admission"
                        % (wait_ms, deadline_ms, req.tier, len(self._heap)),
                        tier=req.tier,
                        retry_after_s=self._retry_after_s(wait_ms),
                        shed_at="admit")
            if len(self._heap) >= self.max_queue:
                # full queue: evict the worst-ranked queued request iff
                # the newcomer strictly outranks it (lowest tier, then
                # latest deadline, then newest — deterministic)
                if self._heap and req < self._heap[-1]:
                    victim = self._heap.pop()
                    self.stats.on_dequeue(1)
                    self.stats.on_shed(victim.tier)
                else:
                    self.stats.on_reject()
                    raise ServerBusy(
                        "request queue full (%d deep); retry later"
                        % self.max_queue) from None
            bisect.insort(self._heap, req)
            self._cond.notify_all()
        if victim is not None:
            victim.set_exception(RequestShed(
                "evicted by a higher-tier arrival under a full queue "
                "(tier=%s)" % victim.tier, tier=victim.tier,
                retry_after_s=self._retry_after_s(self.modeled_wait_ms()),
                shed_at="evict"))
        self.stats.on_submit()
        return req

    def infer(self, example, timeout=30.0, tier=DEFAULT_TIER,
              deadline_ms=None):
        """Blocking convenience: submit + wait for the result row."""
        return self.submit(example, tier=tier,
                           deadline_ms=deadline_ms).result(timeout)

    # -- worker side -------------------------------------------------------
    def _sweep_hopeless_locked(self):
        """Shed queued requests whose deadline can no longer be met given
        their current position and the modeled service time (they would
        rot, occupy queue slots, and waste a device call).  Returns the
        shed list; caller resolves them outside the lock.  Positions run
        in priority order, so lower tiers — parked at the back — see the
        largest modeled wait and are shed first by construction."""
        if not self._heap:
            return []
        now = time.monotonic()
        shed, keep = [], []
        for pos, req in enumerate(self._heap):
            if req.t_deadline is not None and \
                    now + self._modeled_wait_ms(pos) / 1000.0 \
                    > req.t_deadline:
                shed.append(req)
            else:
                keep.append(req)
        if shed:
            self._heap = keep
            self.stats.on_dequeue(len(shed))
            for req in shed:
                self.stats.on_shed(req.tier, swept=True)
        return shed

    def _take_batch(self):
        """Block until work is available, honor the coalescing window,
        shed hopeless requests, and return up to ``max_batch`` requests
        in (tier, deadline, arrival) order.  Returns None when drained
        and empty (worker exit)."""
        with self._cond:
            while not self._heap:
                if self._draining.is_set():
                    return None
                self._cond.wait(timeout=0.1)
            # coalescing window: wait for fill, but close early when the
            # batch is full, drain began, or the most urgent deadline
            # would be burned by further waiting (near-deadline requests
            # go into the NEXT bucket, not one more window later)
            window_end = time.monotonic() + self.batch_timeout_s
            while (len(self._heap) < self.max_batch
                   and not self._draining.is_set()):
                now = time.monotonic()
                remaining = window_end - now
                if remaining <= 0:
                    break
                head_deadline = self._heap[0].t_deadline
                if head_deadline is not None:
                    est_s = (self.est_batch_ms or 0.0) / 1000.0
                    slack = head_deadline - est_s - now
                    if slack <= 0:
                        break
                    remaining = min(remaining, slack)
                self._cond.wait(remaining)
            shed = self._sweep_hopeless_locked()
            batch = self._heap[:self.max_batch]
            del self._heap[:len(batch)]
            if batch:
                self.stats.on_dequeue(len(batch))
        for req in shed:
            req.set_exception(RequestShed(
                "deadline %.0fms unreachable from queue (modeled wait "
                "exceeds remaining budget, tier=%s); shed by sweep"
                % (req.deadline_ms, req.tier), tier=req.tier,
                retry_after_s=self._retry_after_s(self.modeled_wait_ms()),
                shed_at="sweep"))
        return batch

    def _run_batch(self, batch):
        from ..resilience import chaos as _chaos
        self._batch_started = time.monotonic()
        try:
            # chaos probe: a scheduled delay here stalls the runner (the
            # serving-overload failure mode); a raise fails the batch and
            # feeds the fleet's circuit breaker
            _chaos.maybe_inject("serving.batch", ctx=batch)
            n = len(batch)
            bucket = 0   # refined under the runner lock below; a
            #              failure before then reports the 0 bucket
            try:
                x = _np.stack([r.example for r in batch])
                with self._runner_lock:
                    # bucket choice and forward must see the SAME
                    # runner: a hot swap between a bare bucket_for and
                    # the locked forward would pad for the old model
                    # and execute on the new one
                    runner = self.runner
                    bucket = runner.bucket_for(n)
                    out = runner.forward_batch(x)
            except Exception as e:  # propagate per-request, keep serving
                for r in batch:
                    r.set_exception(e)
                self.stats.on_batch(bucket, n, [], error=True,
                                    tiers=[r.tier for r in batch])
                if self.on_batch_error is not None:
                    try:
                        self.on_batch_error(e)
                    except Exception:
                        pass
                return
            now = time.monotonic()
            self._observe_batch_ms((now - self._batch_started) * 1000.0)
            lat = []
            for i, r in enumerate(batch):
                r.set_result(out[i])
                lat.append((now - r.t_submit) * 1000.0)
            self.stats.on_batch(bucket, n, lat,
                                tiers=[r.tier for r in batch])
            self.stats.set_recompiles(runner.recompiles_since_warmup())
            if self.on_batch_success is not None:
                try:
                    self.on_batch_success()
                except Exception:
                    pass
        except Exception as e:
            # a failure outside the runner call (e.g. an injected chaos
            # raise) must not kill the worker: fail the batch, keep going
            for r in batch:
                if not r.done():
                    r.set_exception(e)
            self.stats.on_batch(0, len(batch), [], error=True,
                                tiers=[r.tier for r in batch])
            if self.on_batch_error is not None:
                try:
                    self.on_batch_error(e)
                except Exception:
                    pass
        finally:
            self._batch_started = None

    def _observe_batch_ms(self, measured_ms):
        if self._est_ewma_ms is None:
            self._est_ewma_ms = measured_ms
        else:
            self._est_ewma_ms = 0.7 * self._est_ewma_ms + 0.3 * measured_ms

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                break
            if batch:
                self._run_batch(batch)
        self._drained.set()

    # -- hot swap ----------------------------------------------------------
    def swap_runner(self, runner, timeout=30.0):
        """Replace the model under drain of the in-flight batch: waits
        for the batch currently executing (the runner lock), installs
        ``runner``, and every queued + future request is served by the
        replacement — zero in-flight failures.  The new runner must share
        the old one's ``example_shape`` (queued pixels must stay valid).
        Returns the previous runner; raises ``TimeoutError`` when the
        in-flight batch does not finish in ``timeout``."""
        if not self._runner_lock.acquire(timeout=float(timeout)):
            raise TimeoutError(
                "in-flight batch did not complete within %ss; swap aborted"
                % timeout)
        try:
            # compat check INSIDE the lock region: checked against the
            # runner actually being replaced, not one a concurrent swap
            # may itself be replacing
            if tuple(runner.example_shape) != \
                    tuple(self.runner.example_shape):
                raise MXNetError(
                    "swap refused: example_shape %r != %r — queued "
                    "requests would be fed to an incompatible model"
                    % (tuple(runner.example_shape),
                       tuple(self.runner.example_shape)))
            old, self.runner = self.runner, runner
            with self._cond:
                self.max_batch = min(self._max_batch_req or runner.max_batch,
                                     runner.max_batch)
            self.stats.on_swap()
        finally:
            self._runner_lock.release()
        return old

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout=60.0):
        """Graceful shutdown: stop admitting, finish every queued request,
        join the worker.  Idempotent.  Raises ``TimeoutError`` when the
        deadline passes with work still in flight — callers that must
        stop anyway (``Server.drain``'s hard ``drain_timeout_s``) follow
        up with :meth:`force_drain`."""
        with self._cond:
            self._draining.set()
            self._cond.notify_all()
        if not self._drained.wait(timeout):
            raise TimeoutError("batcher did not drain within %ss" % timeout)
        self._thread.join(timeout=5.0)
        return True

    def force_drain(self):
        """The hard half of the drain deadline: stop admitting, fail every
        request still queued with :class:`Draining`, and mark the batcher
        drained WITHOUT waiting for a wedged worker (a hung model call's
        requests resolve if/when it returns; the daemon worker thread
        dies with the process).  Idempotent; returns the number of
        requests failed."""
        with self._cond:
            self._draining.set()
            stuck, self._heap = self._heap, []
            self._cond.notify_all()
        failed = 0
        for req in stuck:
            self.stats.on_dequeue(1)
            req.set_exception(Draining(
                "server hit its drain deadline; request not served"))
            failed += 1
        self._drained.set()
        return failed

    close = drain
