"""Batcher: coalesce concurrent single requests into bucketed batches.

The dynamic-batching core of the serving layer (the reference analogue is
the server-side request coalescing TF-Serving ships; MXNet's
BucketingModule solved the same compile-explosion problem for training).
A bounded queue feeds one worker thread: the worker takes the first
waiting request, keeps collecting until ``max_batch`` requests are in
hand or ``batch_timeout_ms`` has elapsed, stacks them, and hands the
batch to the :class:`~mxnet_tpu.serving.runner.ModelRunner`, which pads
to the nearest bucket.  Results are split back per-request.

Backpressure: the queue is bounded (``max_queue``); a submit against a
full queue raises :class:`ServerBusy` immediately — callers (the HTTP
front end maps this to 429) retry, the server never builds an unbounded
backlog.  ``drain()`` stops admission, completes everything already
queued, and joins the worker — the graceful-shutdown half of the
contract.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

import numpy as _np

from ..base import MXNetError
from .stats import ServingStats

__all__ = ["Batcher", "ServerBusy", "Draining"]


class ServerBusy(MXNetError):
    """Queue full — reject now rather than stall (HTTP 429)."""


class Draining(MXNetError):
    """Server is draining — no new admissions (HTTP 503)."""


class _Pending:
    """One in-flight request: a tiny future (stdlib-only)."""

    __slots__ = ("example", "_event", "_result", "_exc", "t_submit")

    def __init__(self, example):
        self.example = example
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self.t_submit = time.monotonic()

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_exception(self, exc):
        self._exc = exc
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within %ss" % timeout)
        if self._exc is not None:
            raise self._exc
        return self._result


_SENTINEL = object()


class Batcher:
    def __init__(self, runner, max_batch=None, batch_timeout_ms=2.0,
                 max_queue=256, stats=None):
        self.runner = runner
        self.max_batch = int(max_batch or runner.max_batch)
        if self.max_batch > runner.max_batch:
            # a coalesced batch larger than the top bucket would be split
            # by the runner anyway; cap so one batch == one device call
            self.max_batch = runner.max_batch
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self.stats = stats if stats is not None else \
            ServingStats(runner.buckets)
        self._q = _queue.Queue(maxsize=int(max_queue))
        # serializes admission against drain(): the sentinel must queue
        # strictly after every admitted request or a submit racing drain
        # could land behind the sentinel and never be served
        self._admit_lock = threading.Lock()
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-batcher", daemon=True)
        self._thread.start()

    # -- client side -------------------------------------------------------
    @property
    def queue_depth(self):
        return self._q.qsize()

    @property
    def draining(self):
        return self._draining.is_set()

    def submit(self, example):
        """Enqueue one example; returns a future-like with ``.result()``.
        Raises :class:`ServerBusy` when the queue is full and
        :class:`Draining` after ``drain()`` — never blocks the caller."""
        req = _Pending(_np.asarray(example))
        with self._admit_lock:
            if self._draining.is_set():
                raise Draining("server is draining; request rejected")
            try:
                self._q.put_nowait(req)
            except _queue.Full:
                self.stats.on_reject()
                raise ServerBusy(
                    "request queue full (%d deep); retry later"
                    % self._q.maxsize) from None
        self.stats.on_submit()
        return req

    def infer(self, example, timeout=30.0):
        """Blocking convenience: submit + wait for the result row."""
        return self.submit(example).result(timeout)

    # -- worker side -------------------------------------------------------
    def _collect(self, first):
        """First request in hand: keep collecting until max_batch or the
        coalescing window closes.  Returns (batch, saw_sentinel)."""
        batch = [first]
        deadline = time.monotonic() + self.batch_timeout_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # during drain, whatever is queued should leave in as few
                # device calls as possible — keep filling without waiting
                if self._draining.is_set():
                    try:
                        nxt = self._q.get_nowait()
                    except _queue.Empty:
                        break
                    if nxt is _SENTINEL:
                        return batch, True
                    batch.append(nxt)
                    continue
                break
            try:
                nxt = self._q.get(timeout=remaining)
            except _queue.Empty:
                break
            if nxt is _SENTINEL:
                return batch, True
            batch.append(nxt)
        return batch, False

    def _run_batch(self, batch):
        from ..resilience import chaos as _chaos
        # chaos probe: a scheduled delay here overloads the admission
        # queue deterministically (the serving-overload failure mode)
        _chaos.maybe_inject("serving.batch", ctx=batch)
        self.stats.on_dequeue(len(batch))
        n = len(batch)
        bucket = self.runner.bucket_for(n)
        try:
            x = _np.stack([r.example for r in batch])
            out = self.runner.forward_batch(x)
        except Exception as e:  # propagate per-request, keep serving
            for r in batch:
                r.set_exception(e)
            self.stats.on_batch(bucket, n, [], error=True)
            return
        now = time.monotonic()
        lat = []
        for i, r in enumerate(batch):
            r.set_result(out[i])
            lat.append((now - r.t_submit) * 1000.0)
        self.stats.on_batch(bucket, n, lat)
        self.stats.set_recompiles(self.runner.recompiles_since_warmup())

    def _loop(self):
        while True:
            try:
                req = self._q.get(timeout=0.1)
            except _queue.Empty:
                continue
            if req is _SENTINEL:
                break
            batch, saw_sentinel = self._collect(req)
            self._run_batch(batch)
            if saw_sentinel:
                break
        self._drained.set()

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout=60.0):
        """Graceful shutdown: stop admitting, finish every queued request,
        join the worker.  Idempotent.  Raises ``TimeoutError`` when the
        deadline passes with work still in flight — callers that must
        stop anyway (``Server.drain``'s hard ``drain_timeout_s``) follow
        up with :meth:`force_drain`."""
        with self._admit_lock:
            if not self._draining.is_set():
                self._draining.set()
                # the sentinel queues BEHIND all admitted requests (FIFO),
                # so the worker serves everything in flight before exiting.
                # Blocking put: on a full queue this waits for the worker
                # to make room, which it always does.
                self._q.put(_SENTINEL)
        if not self._drained.wait(timeout):
            raise TimeoutError("batcher did not drain within %ss" % timeout)
        self._thread.join(timeout=5.0)
        return True

    def force_drain(self):
        """The hard half of the drain deadline: stop admitting, fail every
        request still queued with :class:`Draining`, and mark the batcher
        drained WITHOUT waiting for a wedged worker (a hung model call's
        requests resolve if/when it returns; the daemon worker thread
        dies with the process).  Idempotent; returns the number of
        requests failed."""
        with self._admit_lock:
            self._draining.set()
        failed = 0
        while True:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                break
            if req is _SENTINEL:
                continue
            req.set_exception(Draining(
                "server hit its drain deadline; request not served"))
            failed += 1
        self._drained.set()
        return failed

    close = drain
