"""Host-runnable serving micro-benchmark.

Measures ``serving_reqs_per_sec`` plus end-to-end p50/p99 request latency
through the full Runner→Batcher path on whatever backend is available —
it is deliberately TPU-independent so ``bench.py`` can refresh the
serving keys even when the chip never comes up (the r5 failure mode:
every key starved behind backend acquisition).  ``bench.py`` runs this
module as a ``JAX_PLATFORMS=cpu`` subprocess; it can also be run
directly:

    JAX_PLATFORMS=cpu python -m mxnet_tpu.serving.bench
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as _np

__all__ = ["serving_bench"]


def _build_runner(buckets, feat):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from .runner import ModelRunner

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return ModelRunner(net, buckets=buckets, example_shape=(feat,),
                       warmup=True)


def serving_bench(n_requests=None, concurrency=None, buckets=(1, 4, 16, 64),
                  feat=32, batch_timeout_ms=2.0):
    """Fire ``n_requests`` from ``concurrency`` client threads through a
    Batcher over a small MLP; returns the stable bench keys."""
    from .batcher import Batcher

    n_requests = n_requests or int(os.environ.get("MXTPU_SERVING_BENCH_N",
                                                  "400"))
    concurrency = concurrency or int(os.environ.get(
        "MXTPU_SERVING_BENCH_CONCURRENCY", "8"))
    runner = _build_runner(buckets, feat)
    batcher = Batcher(runner, batch_timeout_ms=batch_timeout_ms,
                      max_queue=max(256, n_requests))
    rng = _np.random.RandomState(0)
    examples = rng.rand(64, feat).astype(_np.float32)

    latencies = []
    lat_lock = threading.Lock()
    per_thread = n_requests // concurrency

    def client(tid):
        got = []
        for i in range(per_thread):
            t0 = time.monotonic()
            batcher.infer(examples[(tid + i) % len(examples)], timeout=60)
            got.append((time.monotonic() - t0) * 1000.0)
        with lat_lock:
            latencies.extend(got)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    batcher.drain()

    from .stats import percentile
    served = len(latencies)
    return {
        "serving_reqs_per_sec": round(served / wall, 2) if wall else 0.0,
        "serving_p50_ms": round(percentile(latencies, 50), 3),
        "serving_p99_ms": round(percentile(latencies, 99), 3),
        "serving_batch_fill_ratio": round(
            batcher.stats.batch_fill_ratio(), 4),
        "serving_recompiles": runner.recompiles_since_warmup(),
        "serving_requests": served,
        "serving_concurrency": concurrency,
    }


def main():
    out = serving_bench()
    print(json.dumps(out), flush=True)
    # the contract bench.py's stage relies on: zero steady-state recompiles
    return 0 if out["serving_recompiles"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
