"""Host-runnable serving micro-benchmark.

Measures ``serving_reqs_per_sec`` plus end-to-end p50/p99 request latency
through the full Runner→Batcher path, and the fleet keys — mixed-model
SLO-tiered load through a :class:`ModelFleet` with a degraded-mode
fallback and a mid-run hot swap: per-tier ``serving_tier_<t>_p50/p99_ms``,
``serving_shed_rate``, ``serving_degraded_total``,
``serving_swap_blip_ms`` — on whatever backend is available.  It is
deliberately TPU-independent so ``bench.py`` can refresh the serving keys
even when the chip never comes up (the r5 failure mode: every key starved
behind backend acquisition).  ``bench.py`` runs this module as a
``JAX_PLATFORMS=cpu`` subprocess; it can also be run directly:

    JAX_PLATFORMS=cpu python -m mxnet_tpu.serving.bench
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as _np

__all__ = ["serving_bench"]


def _build_runner(buckets, feat, hidden=64):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from .runner import ModelRunner

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return ModelRunner(net, buckets=buckets, example_shape=(feat,),
                       warmup=True)


def serving_bench(n_requests=None, concurrency=None, buckets=(1, 4, 16, 64),
                  feat=32, batch_timeout_ms=2.0):
    """Fire ``n_requests`` from ``concurrency`` client threads through a
    Batcher over a small MLP; returns the stable bench keys."""
    from .batcher import Batcher

    n_requests = n_requests or int(os.environ.get("MXTPU_SERVING_BENCH_N",
                                                  "400"))
    concurrency = concurrency or int(os.environ.get(
        "MXTPU_SERVING_BENCH_CONCURRENCY", "8"))
    runner = _build_runner(buckets, feat)
    batcher = Batcher(runner, batch_timeout_ms=batch_timeout_ms,
                      max_queue=max(256, n_requests))
    rng = _np.random.RandomState(0)
    examples = rng.rand(64, feat).astype(_np.float32)

    latencies = []
    lat_lock = threading.Lock()
    per_thread = n_requests // concurrency

    def client(tid):
        got = []
        for i in range(per_thread):
            t0 = time.monotonic()
            batcher.infer(examples[(tid + i) % len(examples)], timeout=60)
            got.append((time.monotonic() - t0) * 1000.0)
        with lat_lock:
            latencies.extend(got)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    batcher.drain()

    from .stats import percentile
    served = len(latencies)
    return {
        "serving_reqs_per_sec": round(served / wall, 2) if wall else 0.0,
        "serving_p50_ms": round(percentile(latencies, 50), 3),
        "serving_p99_ms": round(percentile(latencies, 99), 3),
        "serving_batch_fill_ratio": round(
            batcher.stats.batch_fill_ratio(), 4),
        "serving_recompiles": runner.recompiles_since_warmup(),
        "serving_requests": served,
        "serving_concurrency": concurrency,
    }


def fleet_bench(n_requests=None, concurrency=None, buckets=(1, 4, 16),
                feat=32):
    """Mixed-model, SLO-tiered fleet load: a primary MLP plus a cheaper
    variant registered as its degraded-mode fallback, ``concurrency``
    client threads cycling gold/silver/bronze tiers with per-tier
    deadlines, and a hot swap of the primary at the halfway mark.
    Returns the fleet bench keys (per-tier p50/p99, shed_rate,
    swap_blip_ms) — all host-measurable, no TPU required."""
    from .batcher import RequestShed, ServerBusy
    from .fleet import BreakerOpen, ModelFleet
    from .stats import percentile

    n_requests = n_requests or int(os.environ.get(
        "MXTPU_SERVING_BENCH_FLEET_N", "300"))
    concurrency = concurrency or int(os.environ.get(
        "MXTPU_SERVING_BENCH_CONCURRENCY", "8"))
    primary = _build_runner(buckets, feat, hidden=256)
    cheap = _build_runner(buckets, feat, hidden=32)
    fleet = ModelFleet(batch_timeout_ms=1.0, max_queue=64)
    fleet.register("primary", primary, fallback="primary_cheap")
    fleet.register("primary_cheap", cheap)
    spare = _build_runner(buckets, feat, hidden=256)

    # (tier, deadline_ms): gold never sheds, bronze is the shed donor
    ladder = [("gold", 10000.0), ("silver", 2000.0), ("bronze", 40.0)]
    rng = _np.random.RandomState(0)
    examples = rng.rand(64, feat).astype(_np.float32)
    per_thread = n_requests // concurrency
    lock = threading.Lock()
    lat_by_tier = {t: [] for t, _ in ladder}
    dropped = [0]

    def client(tid):
        got = {t: [] for t, _ in ladder}
        drop = 0
        for i in range(per_thread):
            tier, deadline_ms = ladder[(tid + i) % len(ladder)]
            t0 = time.monotonic()
            try:
                fleet.infer(examples[(tid + i) % len(examples)],
                            model="primary", tier=tier,
                            deadline_ms=deadline_ms, timeout=60)
            except (RequestShed, ServerBusy, BreakerOpen):
                drop += 1
                continue
            got[tier].append((time.monotonic() - t0) * 1000.0)
        with lock:
            dropped[0] += drop
            for t, ms in got.items():
                lat_by_tier[t].extend(ms)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    # hot swap the primary mid-burst: the blip is how long the swap
    # waited on the in-flight batch — zero failed in-flight requests
    time.sleep(0.05)
    fleet.swap("primary", spare)
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    fleet.drain()

    served = sum(len(v) for v in lat_by_tier.values())
    stats = fleet.stats_dict()
    out = {
        "serving_fleet_reqs_per_sec": round(served / wall, 2)
        if wall else 0.0,
        "serving_shed_rate": round(
            dropped[0] / float(max(1, served + dropped[0])), 4),
        "serving_degraded_total":
            stats["models"]["primary"]["degraded_total"],
        "serving_swap_blip_ms": stats["models"]["primary"].get(
            "last_swap_blip_ms", 0.0),
        "serving_fleet_recompiles":
            primary.recompiles_since_warmup()
            + spare.recompiles_since_warmup()
            + cheap.recompiles_since_warmup(),
    }
    for tier, _ in ladder:
        ms = lat_by_tier[tier]
        out["serving_tier_%s_p50_ms" % tier] = round(percentile(ms, 50), 3)
        out["serving_tier_%s_p99_ms" % tier] = round(percentile(ms, 99), 3)
    return out


def main():
    out = serving_bench()
    out.update(fleet_bench())
    print(json.dumps(out), flush=True)
    # the contract bench.py's stage relies on: zero steady-state recompiles
    return 0 if (out["serving_recompiles"] == 0
                 and out["serving_fleet_recompiles"] == 0) else 1


if __name__ == "__main__":
    sys.exit(main())
