"""HTTP front end: /predict with dynamic batching, /healthz, /stats.

Stdlib ``http.server`` over the :class:`~mxnet_tpu.serving.batcher.Batcher`
(the socket framing idioms follow ``kvstore_ps.py``: bounded, blocking,
per-connection threads).  Contract:

- ``POST /predict``  body ``{"data": <nested list>}`` — one example when
  the shape matches ``example_shape``, else a batch of examples (each
  coalesced independently).  200 → ``{"outputs": ...}``.
- ``429`` + ``Retry-After`` when the admission queue is full
  (backpressure, never an unbounded backlog), ``503`` while draining,
  ``400`` on malformed bodies, ``500`` on model errors.
- ``GET /healthz`` — readiness-gated summary:
  ``{"status": "ok"|"warming"|"draining", "alive": true, "ready": bool}``
  with 200 only when ready (warming buckets ⇒ ready=false, alive=true —
  a fleet scheduler must not route to a server still compiling its
  bucket ladder, but must not restart it either).
- ``GET /livez`` — liveness alone: 200 while the process serves HTTP at
  all (the restart signal); ``GET /readyz`` — readiness alone (the
  routing signal).
- ``GET /stats`` — the :meth:`ServingStats.as_dict` JSON: per-bucket
  p50/p99 latency, queue depth, batch-fill ratio, recompile count.
- ``drain()`` — stop admissions, finish all in-flight requests, then
  stop the listener (graceful shutdown; wired to SIGTERM/SIGINT in
  ``tools/serve.py``).  Honors a hard deadline (``drain_timeout_s``):
  when in-flight work does not finish in time, queued requests are
  failed with 503s and the listener stops anyway — a wedged model call
  can no longer hold shutdown hostage.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from .batcher import Batcher, Draining, ServerBusy

__all__ = ["Server"]


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # the stdlib default TCP accept backlog is 5: a modest connection
    # burst (tens of clients dialing at once) gets kernel-level RSTs
    # before the app ever sees the requests.  Admission control belongs
    # to the Batcher's bounded queue (429), not the SYN queue.
    request_queue_size = 128


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mxtpu-serving/0.1"

    # the Server instance is attached to the HTTPServer as `.serving`
    @property
    def _srv(self):
        return self.server.serving

    def log_message(self, fmt, *args):  # quiet by default
        if self._srv.verbose:
            super().log_message(fmt, *args)

    def _reply(self, code, payload, headers=()):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self._srv
        if self.path == "/healthz":
            body = {"status": srv.status, "alive": True, "ready": srv.ready}
            self._reply(200 if srv.ready else 503, body)
        elif self.path == "/livez":
            # liveness: answering at all IS the signal — never 503 here,
            # or a fleet manager would restart a server that is merely
            # warming/draining
            self._reply(200, {"alive": True})
        elif self.path == "/readyz":
            self._reply(200 if srv.ready else 503,
                        {"ready": srv.ready, "status": srv.status})
        elif self.path == "/stats":
            stats = srv.batcher.stats.as_dict()
            stats["recompiles"] = srv.runner.recompiles_since_warmup()
            stats["buckets_configured"] = list(srv.runner.buckets)
            # static per-bucket cost model (mxcost): modeled, not
            # measured — lets dashboards show expected flops/HBM next
            # to the measured p50/p99 without a profiling run
            stats["modeled_cost"] = {
                str(b): row
                for b, row in sorted(srv.runner.modeled_cost().items())}
            self._reply(200, stats)
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def do_POST(self):
        if self.path != "/predict":
            self._reply(404, {"error": "unknown path %s" % self.path})
            return
        srv = self._srv
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            data = _np.asarray(payload["data"], dtype=_np.float64)
        except (ValueError, KeyError, TypeError) as e:
            self._reply(400, {"error": "bad request: %s" % e})
            return
        single = data.shape == srv.runner.example_shape
        batch = data[None] if single else data
        if batch.ndim != len(srv.runner.example_shape) + 1 or \
                batch.shape[1:] != srv.runner.example_shape:
            self._reply(400, {
                "error": "shape %r does not match example_shape %r"
                         % (data.shape, srv.runner.example_shape)})
            return
        try:
            pending = [srv.batcher.submit(row) for row in batch]
        except ServerBusy as e:
            self._reply(429, {"error": str(e)},
                        headers=[("Retry-After", "1")])
            return
        except Draining as e:
            self._reply(503, {"error": str(e)})
            return
        try:
            outs = [p.result(srv.request_timeout_s) for p in pending]
        except Exception as e:  # model error / timeout
            self._reply(500, {"error": str(e)[:500]})
            return
        out = _np.stack(outs)
        self._reply(200, {"outputs": (out[0] if single else out).tolist()})


class Server:
    """Ties Runner + Batcher + HTTP listener into one serving process."""

    def __init__(self, runner, host="127.0.0.1", port=8080, max_batch=None,
                 batch_timeout_ms=2.0, max_queue=256,
                 request_timeout_s=30.0, drain_timeout_s=60.0,
                 verbose=False):
        self.runner = runner
        self.batcher = Batcher(runner, max_batch=max_batch,
                               batch_timeout_ms=batch_timeout_ms,
                               max_queue=max_queue)
        self.request_timeout_s = float(request_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.verbose = verbose
        self._httpd = _HTTPServer((host, port), _Handler)
        self._httpd.serving = self
        self._thread = None
        self._drained = False
        self.drain_forced = False

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves to a real one."""
        return self._httpd.server_address[:2]

    @property
    def draining(self):
        return self.batcher.draining

    @property
    def ready(self):
        """Readiness: warmed buckets and not draining.  A runner loaded
        with ``warmup=False`` keeps the server alive-but-unready until
        ``warmup()`` finishes — the liveness/readiness split."""
        return (not self.batcher.draining
                and bool(getattr(self.runner, "warmed_up", True)))

    @property
    def status(self):
        if self.batcher.draining:
            return "draining"
        return "ok" if self.ready else "warming"

    def start(self):
        """Serve in a background thread; returns the bound (host, port)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
                name="mxtpu-http", daemon=True)
            self._thread.start()
        return self.address

    def serve_forever(self):
        """Foreground serve (the tools/serve.py path)."""
        self._httpd.serve_forever(poll_interval=0.1)

    def drain(self, timeout=None):
        """Graceful shutdown with a hard deadline: new requests get 503
        and everything already admitted completes — but only for
        ``drain_timeout_s`` (or ``timeout``).  Past the deadline the
        remaining queue is failed with 503s and the listener stops
        anyway (``drain_forced`` records it): shutdown always finishes.
        Returns True for a clean drain, False when forced."""
        timeout = self.drain_timeout_s if timeout is None else float(timeout)
        try:
            self.batcher.drain(timeout=timeout)
        except TimeoutError:
            self.batcher.force_drain()
            self.drain_forced = True
        if not self._drained:
            self._drained = True
            # shutdown() blocks until serve_forever exits; in-flight
            # handler threads (daemon, already answered by the drained
            # batcher) finish their writes independently
            threading.Thread(target=self._httpd.shutdown,
                             daemon=True).start()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            self._httpd.server_close()
        return not self.drain_forced

    stop = drain
