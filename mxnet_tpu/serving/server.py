"""HTTP front end: /predict with multi-model routing and SLO tiers,
/healthz, /livez, /readyz (per-model), /stats.

Stdlib ``http.server`` over a :class:`~mxnet_tpu.serving.fleet.ModelFleet`
(the socket framing idioms follow ``kvstore_ps.py``: bounded, blocking,
per-connection threads).  A bare :class:`ModelRunner` is accepted too and
wrapped as a one-model fleet named ``default``.  Contract:

- ``POST /predict``  body ``{"data": <nested list>, "model": <name>,
  "tier": "gold"|"silver"|"bronze", "deadline_ms": <number>}`` (model/
  tier/deadline optional — defaults: the fleet's default model, gold, no
  deadline).  ``data`` is one example when the shape matches the routed
  model's ``example_shape``, else a batch of examples (each coalesced
  independently).  200 → ``{"outputs": ..., "model": name}``.
- ``POST /decode``  body ``{"prompt": [token ids], "model": <name>,
  "max_new_tokens": <int>, "tier": ..., "deadline_ms": ...}`` against a
  registered :class:`~mxnet_tpu.serving.decode.DecodeRunner` — 200 →
  ``{"tokens": [...], "model": name}``; 400 when the routed model is
  fixed-shape.  Refusal codes match ``/predict``.
- ``429`` + ``Retry-After`` when the admission queue is full
  (backpressure), ``503`` + ``Retry-After`` when admission control sheds
  the request (modeled queue wait past its deadline, eviction by a
  higher tier, or an open circuit breaker) or while draining, ``404`` on
  an unknown model, ``400`` on malformed bodies, ``413`` when the body
  exceeds ``max_body_bytes`` (the handler never buffers an unbounded
  POST), ``500`` on model errors.
- ``GET /livez`` — liveness alone: 200 while the process serves HTTP at
  all (the restart signal).  ``GET /readyz`` — the routing signal, now
  per-model: 503 with ``{"unready": {model: reason}}`` until every
  registered model is warm, its breaker closed, and nothing is stalled
  or draining.  ``GET /healthz`` keeps the readiness-gated summary.
- ``GET /stats`` — the default model's ServingStats dict (back-compat
  flat keys) plus ``models`` with every model's stats, breaker state,
  per-tier p50/p99/shed, modeled HBM packing ledger and swap blips.
- ``GET /metrics`` — the process-wide telemetry registry in Prometheus
  text exposition format (``text/plain; version=0.0.4``): the same
  serving numbers as gauges/summaries plus every other registered
  source (pipeline, dispatch, PS tier) — docs/observability.md.
- ``drain()`` — stop admissions, finish all in-flight requests, then
  stop the listener (graceful shutdown; wired to SIGTERM/SIGINT in
  ``tools/serve.py``).  Honors a hard deadline (``drain_timeout_s``).

All latency/drain arithmetic is ``time.monotonic()``-based (audited: no
wall-clock ``time.time()`` in the serving path — an NTP step must never
expire a deadline or a drain early).
"""
from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from ..base import MXNetError
from .batcher import Draining, RequestShed, ServerBusy, tier_rank
from .fleet import BreakerOpen, ModelFleet, UnknownModel

__all__ = ["Server"]

# bound on request bodies the handler will buffer; an oversized POST gets
# 413 without reading the payload (OOM-proofing the handler thread)
DEFAULT_MAX_BODY_BYTES = 16 << 20


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # the stdlib default TCP accept backlog is 5: a modest connection
    # burst (tens of clients dialing at once) gets kernel-level RSTs
    # before the app ever sees the requests.  Admission control belongs
    # to the Batcher's bounded queue (429), not the SYN queue.
    request_queue_size = 128


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mxtpu-serving/0.2"

    # the Server instance is attached to the HTTPServer as `.serving`
    @property
    def _srv(self):
        return self.server.serving

    def log_message(self, fmt, *args):  # quiet by default
        if self._srv.verbose:
            super().log_message(fmt, *args)

    def _reply(self, code, payload, headers=()):
        body = json.dumps(payload).encode()
        self._reply_raw(code, body, "application/json", headers)

    def _reply_raw(self, code, body, content_type, headers=()):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self._srv
        if self.path == "/healthz":
            body = {"status": srv.status, "alive": True, "ready": srv.ready,
                    # the hello-path provenance surface: which checkpoint
                    # bytes each model serves (digest or null) — the
                    # quick answer to "what is live right now?"
                    "provenance": srv.fleet.provenance_digests()}
            self._reply(200 if srv.ready else 503, body)
        elif self.path == "/livez":
            # liveness: answering at all IS the signal — never 503 here,
            # or a fleet manager would restart a server that is merely
            # warming/draining/tripped
            self._reply(200, {"alive": True})
        elif self.path == "/readyz":
            # the routing signal, per-model: a fleet scheduler must not
            # send traffic while any registered model is cold, tripped,
            # stalled or draining — but must not restart the process
            unready = srv.fleet.unready()
            if srv.draining:
                unready = dict(unready, **{
                    m: "draining" for m in srv.fleet.models()
                    if m not in unready})
            ready = not unready and not srv.draining
            body = {"ready": ready, "status": srv.status}
            if unready:   # per-model detail only when something is wrong
                body["unready"] = unready
            self._reply(200 if ready else 503, body)
        elif self.path == "/stats":
            fleet_stats = srv.fleet.stats_dict()
            # back-compat flat surface: the default model's numbers at
            # the top level, exactly what single-model dashboards read
            default = srv.fleet.entry()
            stats = default.batcher.stats.as_dict()
            stats["recompiles"] = default.runner.recompiles_since_warmup()
            stats["buckets_configured"] = list(default.runner.buckets)
            # static per-bucket cost model (mxcost): modeled, not
            # measured — lets dashboards show expected flops/HBM next
            # to the measured p50/p99 without a profiling run.  Decode
            # runners price admission by pages, not per-bucket cost
            # rows, so the key is absent when the default model decodes.
            if hasattr(default.runner, "modeled_cost"):
                stats["modeled_cost"] = {
                    str(b): row
                    for b, row in
                    sorted(default.runner.modeled_cost().items())}
            stats.update(fleet_stats)
            self._reply(200, stats)
        elif self.path == "/metrics":
            # the one-pane scrape surface: the process-wide telemetry
            # registry (serving stats, breakers, pipeline/dispatch
            # counters, PS gauges — whatever registered) in Prometheus
            # text exposition format
            from .. import telemetry as _tele
            self._reply_raw(200, _tele.registry().prometheus_text()
                            .encode(), "text/plain; version=0.0.4")
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def do_POST(self):
        if self.path not in ("/predict", "/decode"):
            self._reply(404, {"error": "unknown path %s" % self.path})
            return
        srv = self._srv
        try:
            n = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            self._reply(400, {"error": "bad Content-Length"})
            return
        if n > srv.max_body_bytes:
            # refuse BEFORE reading: an unbounded read here is how an
            # oversized POST OOMs the handler thread.  The unread body
            # makes the connection unreusable — close it.
            self.close_connection = True
            self._reply(413, {
                "error": "request body %d bytes exceeds the %d-byte cap"
                         % (n, srv.max_body_bytes)},
                headers=[("Connection", "close")])
            return
        try:
            payload = json.loads(self.rfile.read(n) or b"{}")
        except ValueError as e:
            self._reply(400, {"error": "bad request: %s" % e})
            return
        if self.path == "/decode":
            self._do_decode(payload)
            return
        try:
            data = _np.asarray(payload["data"], dtype=_np.float64)
            model = payload.get("model")
            tier = payload.get("tier", "gold")
            deadline_ms = payload.get("deadline_ms")
            # request_id seeds the deterministic canary hash split; a
            # client that wants stable variant assignment (or replayable
            # routing) sends one — absent, the route ordinal is used
            request_id = payload.get("request_id")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            tier_rank(tier)  # validate before routing: bad tier is a 400
        except (ValueError, KeyError, TypeError, MXNetError) as e:
            self._reply(400, {"error": "bad request: %s" % e})
            return
        try:
            entry = srv.fleet.entry(model)
        except UnknownModel as e:
            self._reply(404, {"error": str(e)})
            return
        if getattr(entry.runner, "example_shape", None) is None:
            # decode runners take variable-length token prompts, not
            # fixed-shape examples — route them to /decode
            self._reply(400, {
                "error": "model %r is an autoregressive decode model; "
                         "POST /decode" % entry.name})
            return
        example_shape = tuple(entry.runner.example_shape)
        single = data.shape == example_shape
        batch = data[None] if single else data
        if batch.ndim != len(example_shape) + 1 or \
                batch.shape[1:] != example_shape:
            self._reply(400, {
                "error": "shape %r does not match model %r example_shape "
                         "%r" % (data.shape, entry.name, example_shape)})
            return
        try:
            pending = [srv.fleet.submit(row, model=entry.name, tier=tier,
                                        deadline_ms=deadline_ms,
                                        request_id=request_id)
                       for row in batch]
            outs = [p.result(srv.request_timeout_s) for p in pending]
        except ServerBusy as e:
            self._reply(429, {"error": str(e)},
                        headers=[("Retry-After", "1")])
            return
        except (RequestShed, BreakerOpen) as e:
            retry = max(1, int(math.ceil(getattr(e, "retry_after_s", 1.0))))
            self._reply(503, {"error": str(e),
                              "tier": getattr(e, "tier", tier)},
                        headers=[("Retry-After", str(retry))])
            return
        except Draining as e:
            self._reply(503, {"error": str(e)})
            return
        except Exception as e:  # model error / timeout
            self._reply(500, {"error": str(e)[:500]})
            return
        out = _np.stack(outs)
        self._reply(200, {"outputs": (out[0] if single else out).tolist(),
                          "model": entry.name})

    def _do_decode(self, payload):
        """``POST /decode`` — the autoregressive route: ``{"prompt":
        [token ids], "model": <name>, "max_new_tokens": <int>, "tier":
        ..., "deadline_ms": ...}`` → 200 ``{"tokens": [...], "model":
        name}``.  Same refusal surface as ``/predict`` (429 queue-full,
        503 shed/breaker/draining, 404 unknown model) plus 400 when the
        routed model is a fixed-shape one — decode requests only make
        sense against a registered DecodeRunner."""
        srv = self._srv
        try:
            prompt = _np.asarray(payload["prompt"], dtype=_np.int32)
            if prompt.ndim != 1 or prompt.size < 1:
                raise ValueError("prompt must be a non-empty 1-D "
                                 "token-id list")
            model = payload.get("model")
            tier = payload.get("tier", "gold")
            max_new = int(payload.get("max_new_tokens", 16))
            deadline_ms = payload.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            tier_rank(tier)
        except (ValueError, KeyError, TypeError) as e:
            self._reply(400, {"error": "bad request: %s" % e})
            return
        try:
            entry = srv.fleet.entry(model)
        except UnknownModel as e:
            self._reply(404, {"error": str(e)})
            return
        try:
            out = srv.fleet.decode(prompt, model=entry.name,
                                   max_new_tokens=max_new,
                                   timeout=srv.request_timeout_s,
                                   tier=tier, deadline_ms=deadline_ms)
        except ServerBusy as e:
            self._reply(429, {"error": str(e)},
                        headers=[("Retry-After", "1")])
            return
        except (RequestShed, BreakerOpen) as e:
            retry = max(1, int(math.ceil(getattr(e, "retry_after_s", 1.0))))
            self._reply(503, {"error": str(e),
                              "tier": getattr(e, "tier", tier)},
                        headers=[("Retry-After", str(retry))])
            return
        except Draining as e:
            self._reply(503, {"error": str(e)})
            return
        except MXNetError as e:
            # a fixed-shape model on the decode route (or vice versa)
            self._reply(400, {"error": str(e)})
            return
        except Exception as e:  # model error / timeout
            self._reply(500, {"error": str(e)[:500]})
            return
        self._reply(200, {"tokens": _np.asarray(out).tolist(),
                          "model": entry.name})


class Server:
    """Ties Fleet (or a single Runner) + HTTP listener into one serving
    process.  With a bare runner, ``max_batch``/``batch_timeout_ms``/
    ``max_queue`` configure its batcher exactly as before; with a
    pre-built :class:`ModelFleet` those knobs live on the fleet's
    registrations and are ignored here."""

    def __init__(self, model, host="127.0.0.1", port=8080, max_batch=None,
                 batch_timeout_ms=2.0, max_queue=256,
                 request_timeout_s=30.0, drain_timeout_s=60.0,
                 max_body_bytes=DEFAULT_MAX_BODY_BYTES, verbose=False):
        if isinstance(model, ModelFleet):
            self.fleet = model
        else:
            self.fleet = ModelFleet(batch_timeout_ms=batch_timeout_ms,
                                    max_queue=max_queue)
            self.fleet.register("default", model, max_batch=max_batch)
        self.request_timeout_s = float(request_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        self.verbose = verbose
        self._httpd = _HTTPServer((host, port), _Handler)
        self._httpd.serving = self
        self._thread = None
        self._drained = False
        self.drain_forced = False

    # back-compat single-model surface (PR-2 callers/tests): the default
    # model's runner/batcher, following hot swaps
    @property
    def runner(self):
        return self.fleet.entry().runner

    @property
    def batcher(self):
        return self.fleet.entry().batcher

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves to a real one."""
        return self._httpd.server_address[:2]

    @property
    def draining(self):
        return self.fleet.draining

    @property
    def ready(self):
        """Readiness: every registered model warm, breaker closed, not
        stalled, and nothing draining — the per-model liveness/readiness
        split ``/readyz`` serves."""
        return not self.draining and self.fleet.ready

    @property
    def status(self):
        if self.draining:
            return "draining"
        return "ok" if self.ready else "warming"

    def start(self):
        """Serve in a background thread; returns the bound (host, port)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
                name="mxtpu-http", daemon=True)
            self._thread.start()
        return self.address

    def serve_forever(self):
        """Foreground serve (the tools/serve.py path)."""
        self._httpd.serve_forever(poll_interval=0.1)

    def drain(self, timeout=None):
        """Graceful shutdown with a hard deadline: new requests get 503
        and everything already admitted completes — but only for
        ``drain_timeout_s`` (or ``timeout``).  Past the deadline the
        remaining queues are failed with 503s and the listener stops
        anyway (``drain_forced`` records it): shutdown always finishes.
        Returns True for a clean drain, False when forced."""
        timeout = self.drain_timeout_s if timeout is None else float(timeout)
        try:
            self.fleet.drain(timeout=timeout)
        except TimeoutError:
            self.fleet.force_drain()
            self.drain_forced = True
        if not self._drained:
            self._drained = True
            # shutdown() blocks until serve_forever exits; in-flight
            # handler threads (daemon, already answered by the drained
            # batcher) finish their writes independently
            threading.Thread(target=self._httpd.shutdown,
                             daemon=True).start()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            self._httpd.server_close()
        return not self.drain_forced

    stop = drain
