"""Real post-training quantization (PTQ) for the serving fleet.

Replaces the naive quantize-at-load orphan (``tools/serve.py`` used to
call ``contrib.quantization.quantize_model`` over SYNTHETIC calibration
data) with a pipeline whose every number is accountable
(docs/precision.md):

- **Per-channel weight scales**: each output channel quantizes against
  its own ``amax/127`` — one outlier row no longer poisons the whole
  tensor's resolution the way a per-tensor (min, max) pair does.
- **Calibration from a real set**: activation ranges come from forward
  passes over caller-provided calibration batches, never synthetic
  noise.
- **int8 matmul via the ``qmm_requant`` lineage**: the quantized layers
  lower to ``_contrib_quantized_fc_pc`` (ops/quantization.py) — s8×s8
  →s32 on the MXU with the per-channel dequant + bias + relu epilogue
  fused, int32 accumulator never touching HBM.
- **Scales carry provenance**: :func:`ptq_digest` hashes every code
  tensor, scale vector and calibrated range into one sha256 that rides
  the runner's ``provenance`` dict — the digest the fleet ``/stats``
  and promotion audit records name.  Two quantizations of the same
  checkpoint over the same calibration set digest identically; a
  scrambled scale does not.

The quantized model registers as an ordinary fleet variant, so its
golden-set parity is judged by the PR-12
:class:`~mxnet_tpu.mlops.promote.PromotionController` exactly like any
canary: a bad quant (scrambled scales, wrong calibration) drops
``golden_parity`` below the threshold and auto-rolls-back with the
audit record naming the metric (tests/test_precision.py).

Scope: the gluon path quantizes Dense chains (the fleet's MLP serving
models) per-channel; Module/symbol checkpoints route through
:func:`ptq_quantize_module` — the contrib graph rewrite driven by REAL
calibration data with the scales digested — because per-channel scale
plumbing through the reference's (data, min, max) triple ABI would fork
that contract.
"""
from __future__ import annotations

import hashlib

import numpy as _np

from ..base import MXNetError

__all__ = ["PTQLayer", "PTQModel", "ptq_quantize_net", "ptq_digest",
           "build_quantized_net", "QuantizedDense",
           "quantized_runner_from_checkpoint", "ptq_quantize_module",
           "per_channel_scales"]


def per_channel_scales(w):
    """Symmetric per-output-channel int8 scales of a ``(O, I)`` weight:
    ``scales[c] = amax(|w[c, :]|) / 127`` (floored so an all-zero
    channel quantizes to code 0, not NaN).  Returns ``(codes int8,
    scales f32 (O,))``."""
    w = _np.asarray(w, _np.float32)
    flat = w.reshape(w.shape[0], -1)
    scales = _np.abs(flat).max(axis=1) / 127.0
    scales = _np.maximum(scales, 1e-12).astype(_np.float32)
    codes = _np.clip(_np.round(flat / scales[:, None]), -127, 127) \
        .astype(_np.int8)
    return codes.reshape(w.shape), scales


class PTQLayer:
    """One quantized Dense layer: int8 codes, per-channel scales, the
    f32 bias, the CALIBRATED input amax and the activation to fuse."""

    __slots__ = ("name", "codes", "scales", "bias", "in_amax",
                 "activation", "units")

    def __init__(self, name, codes, scales, bias, in_amax,
                 activation=None):
        self.name = str(name)
        self.codes = _np.asarray(codes, _np.int8)
        self.scales = _np.asarray(scales, _np.float32)
        self.bias = None if bias is None \
            else _np.asarray(bias, _np.float32)
        self.in_amax = float(in_amax)
        self.activation = activation
        self.units = int(self.codes.shape[0])


class PTQModel:
    """The pipeline's output: the ordered quantized layers plus the
    calibration summary.  ``digest`` is memoized content identity over
    every scale/code/range byte (:func:`ptq_digest`)."""

    def __init__(self, layers, calib_examples):
        self.layers = list(layers)
        self.calib_examples = int(calib_examples)
        self._digest = None

    @property
    def digest(self):
        if self._digest is None:
            self._digest = ptq_digest(self)
        return self._digest

    def describe(self):
        return {
            "layers": [{"name": l.name,
                        "units": l.units,
                        "in_amax": round(l.in_amax, 6),
                        "scale_min": float(l.scales.min()),
                        "scale_max": float(l.scales.max())}
                       for l in self.layers],
            "calib_examples": self.calib_examples,
            "digest": self.digest,
        }


def ptq_digest(model):
    """sha256 over every quantized artifact — codes, per-channel
    scales, biases and calibrated ranges in layer order.  The
    provenance identity of a quantization: same checkpoint + same
    calibration set → same digest; a scrambled scale changes it."""
    h = hashlib.sha256()
    for layer in model.layers:
        h.update(layer.name.encode())
        h.update(_np.ascontiguousarray(layer.codes).tobytes())
        h.update(_np.ascontiguousarray(layer.scales).tobytes())
        if layer.bias is not None:
            h.update(_np.ascontiguousarray(layer.bias).tobytes())
        h.update(_np.float32(layer.in_amax).tobytes())
        h.update(str(layer.activation).encode())
    return h.hexdigest()


def _dense_layers(net):
    """Flatten a gluon net into its ordered Dense children; anything
    else (activations live INSIDE Dense here) is a scope error — the
    pipeline quantizes what it can prove it understands."""
    from ..gluon import nn

    out = []

    def walk(block):
        if isinstance(block, nn.Dense):
            out.append(block)
            return
        kids = list(getattr(block, "_children", {}).values())
        if not kids:
            raise MXNetError(
                "ptq_quantize_net only quantizes Dense chains; found "
                "%r with no Dense children" % type(block).__name__)
        for child in kids:
            walk(child)

    walk(net)
    if not out:
        raise MXNetError("no Dense layers found to quantize")
    return out


def ptq_quantize_net(net, calib):
    """Quantize a trained Dense-chain gluon net from a REAL calibration
    set: per-channel weight scales, per-layer input amax measured by
    running ``calib`` through the f32 layers in order.  Returns a
    :class:`PTQModel`."""
    from .. import ndarray as nd

    calib = _np.asarray(calib, _np.float32)
    if calib.ndim < 2 or calib.shape[0] < 1:
        raise MXNetError("calibration set must be (n,) + example_shape "
                         "with n >= 1, got %r" % (calib.shape,))
    layers = []
    x = nd.array(calib)
    for dense in _dense_layers(net):
        w = dense.weight.data().asnumpy()
        bias = dense.bias.data().asnumpy() if dense.bias is not None \
            else None
        codes, scales = per_channel_scales(w)
        in_amax = max(float(_np.abs(x.asnumpy()).max()), 1e-12)
        act = dense.act._act_type if dense.act is not None else None
        layers.append(PTQLayer(dense.name, codes, scales, bias, in_amax,
                               activation=act))
        x = dense(x)    # f32 forward feeds the NEXT layer's calibration
    return PTQModel(layers, calib.shape[0])


_QDENSE_CLS = None


def _quantized_dense_cls():
    """Lazily define (and cache) QuantizedDense — serving.quantize must
    import without dragging the gluon tier in at module load."""
    global _QDENSE_CLS
    if _QDENSE_CLS is not None:
        return _QDENSE_CLS
    from ..gluon.block import HybridBlock

    class QuantizedDense(HybridBlock):
        """One PTQ'd Dense layer: int8 codes + per-channel scales as
        gluon Constants, lowered through ``_contrib_quantized_fc_pc``
        (the qmm_requant-lineage fused epilogue).  relu fuses into the
        epilogue; other activations apply on the float rail after."""

        def __init__(self, layer, prefix=None, params=None):
            super().__init__(prefix=prefix, params=params)
            from .. import ndarray as nd
            self._units = layer.units
            self._in_amax = layer.in_amax
            self._activation = layer.activation
            with self.name_scope():
                self.wq = self.params.get_constant(
                    "wq", nd.array(layer.codes, dtype=_np.int8))
                self.wscale = self.params.get_constant(
                    "wscale", nd.array(layer.scales, dtype=_np.float32))
                self.bias = None if layer.bias is None else \
                    self.params.get_constant(
                        "bias", nd.array(layer.bias, dtype=_np.float32))

        def hybrid_forward(self, F, x, wq, wscale, bias=None):
            out = F.contrib.quantized_fc_pc(
                x, wq, wscale, bias, num_hidden=self._units,
                in_amax=self._in_amax, relu=self._activation == "relu",
                no_bias=bias is None)
            if self._activation not in (None, "relu"):
                out = F.Activation(out, act_type=self._activation)
            return out

    _QDENSE_CLS = QuantizedDense
    return QuantizedDense


def __getattr__(name):
    if name == "QuantizedDense":
        return _quantized_dense_cls()
    raise AttributeError(name)


def build_quantized_net(model):
    """A hybridized gluon net serving a :class:`PTQModel` — what a
    :class:`~mxnet_tpu.serving.runner.ModelRunner` wraps."""
    from ..gluon import nn

    cls = _quantized_dense_cls()
    net = nn.HybridSequential()
    for layer in model.layers:
        net.add(cls(layer))
    net.initialize()
    net.hybridize()
    return net


def quantized_runner_from_checkpoint(path_or_record, net_builder,
                                     example_shape, calib,
                                     buckets=(1, 4, 16), **runner_kwargs):
    """The PTQ twin of
    :func:`~mxnet_tpu.mlops.promote.runner_from_trainer_checkpoint`:
    rebuild the f32 net from a trainer ``.mxckpt`` snapshot, quantize
    it over the REAL calibration set, and wrap the quantized net in a
    :class:`~mxnet_tpu.serving.runner.ModelRunner` whose provenance
    carries BOTH the checkpoint digest and the quantization digest —
    the promotion controller judges the variant like any canary.

    Returns ``(runner, provenance, ptq_model)`` — the PTQModel rides
    along so callers (and tests) can inspect or deliberately break the
    scales and rebuild via :func:`build_quantized_net`."""
    from ..mlops.promote import runner_from_trainer_checkpoint
    from ..resilience import checkpoint as _ckpt
    from .runner import ModelRunner

    if isinstance(path_or_record, dict):
        rec = path_or_record
    else:
        rec = _ckpt.load_checkpoint(path_or_record)
    # reuse the positional param-mapping discipline (shape checks and
    # all) by building the f32 runner, then quantizing its net
    f32_runner, prov = runner_from_trainer_checkpoint(
        rec, net_builder, example_shape=example_shape, buckets=buckets)
    model = ptq_quantize_net(f32_runner._model, calib)
    qnet = build_quantized_net(model)
    prov = dict(prov or {})
    prov["quant_digest"] = model.digest
    prov["quant"] = {"kind": "ptq_per_channel",
                     "calib_examples": model.calib_examples}
    runner = ModelRunner(qnet, buckets=buckets,
                         example_shape=tuple(example_shape),
                         provenance=prov, **runner_kwargs)
    return runner, prov, model


def ptq_quantize_module(sym, arg_params, aux_params, calib_data,
                        data_names=("data",), num_calib_examples=None,
                        calib_mode="naive", excluded_sym_names=None):
    """PTQ for Module/symbol checkpoints (the ``tools/serve.py :int8``
    route): the contrib graph rewrite driven by a REAL calibration
    iterator — never synthetic — with every weight scale and calibrated
    range digested for provenance.  Per-tensor scales here (the
    reference triple ABI); the per-channel story is the gluon path
    above.  Returns ``(qsym, qarg, aux, report)`` where ``report`` has
    the sha256 ``digest`` the serving provenance carries."""
    from ..contrib.quantization import quantize_model

    if calib_data is None:
        raise MXNetError(
            "ptq_quantize_module needs a real calibration iterator — "
            "the synthetic-data shortcut is exactly the naive-at-load "
            "path this pipeline retires (pass tools/serve.py --calib)")
    qsym, qarg, aux = quantize_model(
        sym, arg_params, aux_params, data_names=tuple(data_names),
        calib_mode=calib_mode, calib_data=calib_data,
        num_calib_examples=num_calib_examples,
        excluded_sym_names=excluded_sym_names)
    h = hashlib.sha256()
    for name in sorted(qarg):
        if name.endswith(("_quantized", "_min", "_max")):
            h.update(name.encode())
            h.update(_np.ascontiguousarray(
                qarg[name].asnumpy()).tobytes())
    report = {"digest": h.hexdigest(),
              "calib_mode": str(calib_mode),
              "kind": "ptq_per_tensor_module"}
    return qsym, qarg, aux, report
