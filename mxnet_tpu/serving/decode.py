"""KV-cache autoregressive serving: paged allocation, prefill/decode
split, and continuous batching.

The serve-side twin of the PR-14 transformer tier — the fleet can now
serve the model the repo trains.  Three layers over
:class:`~mxnet_tpu.transformer.decode.DecodeProgram`:

- :class:`PagePool` — the host-side page allocator for the device KV
  pools: fixed ``page_size``-token blocks, allocated ascending and
  recycled LIFO (deterministic), with page 0 reserved as the device
  scratch page (idle slots and overruns land there by construction).
  Admission control counts *pages*, not worst-case sequences — the
  SRV004 packing story extended to the decode tier.
- :class:`DecodeRunner` — a trained TransformerLM behind the two-phase
  recompile-free ladder: prefill compiles once per length bucket (page
  multiples, AOT-warmed), decode compiles ONCE for the fixed slot
  batch, and the jit-cache key set is exposed so steady-state decode
  provably never recompiles (the PR-2 ``ModelRunner`` contract,
  generalized).
- :class:`DecodeBatcher` — **continuous batching**: one worker owns a
  fixed set of decode slots; sequences join the running batch the step
  a slot and enough pages free up, leave the step they finish, and the
  SLO-tier/shed/deadline arithmetic is generalized from per-request to
  **tokens-remaining** — modeled completion = (slot wait + queue-ahead
  amortized over slots + the request's own token budget) × the
  EWMA-or-pinned per-token step time.  Shed decisions are deterministic
  under a pinned ``token_time_hint_ms`` and sequential submission (the
  chaos/determinism tests replay byte-identical join/leave/shed
  schedules via :meth:`DecodeBatcher.schedule_events`).

Locking (docs/concurrency.md): ``_cond`` guards the queue, the slot
table, the page pool bookkeeping and the schedule log; ``_runner_lock``
is held only around the device call; they never nest.  The runner's own
``_lock`` guards the cache pools.  All timing is ``time.monotonic()``
(SRV005 discipline).  Chaos probe: the worker fires the registered
``serving.batch`` site once per decode step — an injected raise fails
every active sequence *and frees its pages* (the no-leak contract the
chaos test pins).
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque

import numpy as _np

from ..base import MXNetError
from .batcher import (DEFAULT_TIER, Draining, RequestShed, ServerBusy,
                      tier_name, tier_rank)
from .stats import _WINDOW, ServingStats, percentile

__all__ = ["PagePool", "NoPagesFree", "DecodeRunner", "DecodeBatcher",
           "DecodeStats"]


class NoPagesFree(MXNetError):
    """The page pool cannot cover a sequence's token budget right now —
    the decode tier's ServerBusy analogue (HTTP 429 at the /decode
    surface; queued requests simply wait for reclaimed pages)."""


class PagePool:
    """Host-side allocator over a device KV pool of ``n_pages`` blocks.

    Page 0 is the reserved scratch page (never handed out): idle batch
    slots carry all-zero page tables and sequence overruns write/read
    scratch, so a bookkeeping bug can corrupt garbage but never a live
    sequence.  Allocation is ascending-first with LIFO recycling —
    byte-identical page assignments across seeded reruns.

    NOT internally locked: the owner serializes access (the
    DecodeBatcher under its ``_cond``, a standalone DecodeRunner under
    its ``_lock``) — one pool must not be shared between both uses.
    """

    def __init__(self, n_pages, page_size, bytes_per_page):
        n_pages = int(n_pages)
        if n_pages < 2:
            raise MXNetError("PagePool needs >= 2 pages (page 0 is "
                             "scratch), got %d" % n_pages)
        self.n_pages = n_pages
        self.page_size = int(page_size)
        self.bytes_per_page = int(bytes_per_page)
        # descending so .pop() hands out ascending ids; freed pages are
        # pushed back on top (LIFO) — both deterministic
        self._free = list(range(n_pages - 1, 0, -1))
        self._leased = 0

    @property
    def available(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self._leased

    def pages_for(self, n_tokens):
        return -(-int(n_tokens) // self.page_size)

    def alloc(self, n):
        """Lease ``n`` pages; raises :class:`NoPagesFree` when the pool
        cannot cover them (callers check :attr:`available` first on the
        admission path — the raise is the belt-and-braces error)."""
        n = int(n)
        if n > len(self._free):
            raise NoPagesFree(
                "%d pages requested, %d free (of %d; %d leased)"
                % (n, len(self._free), self.n_pages - 1, self._leased))
        pages = [self._free.pop() for _ in range(n)]
        self._leased += n
        return pages

    def free(self, pages):
        """Return a lease.  Double-frees raise — a page on two
        sequences' tables is exactly the corruption the scratch-page
        design exists to rule out."""
        for p in pages:
            if p <= 0 or p >= self.n_pages or p in self._free:
                raise MXNetError("bad page free: %r (free list %d/%d)"
                                 % (p, len(self._free), self.n_pages))
        self._free.extend(reversed(pages))
        self._leased -= len(pages)

    def describe(self):
        return {"n_pages": self.n_pages, "page_size": self.page_size,
                "bytes_per_page": self.bytes_per_page,
                "available": self.available,
                "pages_in_use": self.pages_in_use}


def _default_prefill_buckets(page_size, seq_len):
    """Doubling ladder of page multiples up to the context length —
    the PR-2 bucket discipline with page-aligned rungs."""
    out, b = [], page_size
    while b < seq_len:
        out.append(b)
        b *= 2
    out.append(seq_len)
    return tuple(sorted(set(out)))


class DecodeRunner:
    """A trained TransformerLM behind the recompile-free prefill/decode
    ladder and a paged KV pool.

    Parameters
    ----------
    program : DecodeProgram (or a TransformerLMConfig, wrapped with the
        collapsed single-host plan)
    params : dict name -> GLOBAL float32 array (``MeshProgram``
        parameter layout — what ``init_params`` / a training checkpoint
        holds); sharding to model ranks happens inside the jitted
        ``shard_map`` programs.
    n_pages : KV pool size in pages, scratch included (default: every
        slot can hold one full-context sequence).
    prefill_buckets : prompt length ladder (page multiples, each
        compiled AOT); default doubling page multiples up to seq_len.
    slots : the fixed decode batch width — continuous batching joins and
        leaves within these slots, so decode compiles exactly once.
    """

    def __init__(self, program, params, n_pages=None, prefill_buckets=None,
                 slots=4, mesh=None, warmup=True, provenance=None):
        from ..transformer.decode import DecodeProgram
        if not isinstance(program, DecodeProgram):
            program = DecodeProgram(program)
        self.program = program
        self.page_size = program.page_size
        self.pages_per_seq = program.pages_per_seq
        self.slots = int(slots)
        if self.slots < 1:
            raise MXNetError("DecodeRunner needs >= 1 slot")
        if n_pages is None:
            n_pages = 1 + self.slots * self.pages_per_seq
        if prefill_buckets is None:
            prefill_buckets = _default_prefill_buckets(
                self.page_size, program.cfg.seq_len)
        self.buckets = tuple(sorted(int(b) for b in set(prefill_buckets)))
        for b in self.buckets:
            if b % self.page_size or b > program.cfg.seq_len or b < 1:
                raise MXNetError(
                    "prefill buckets must be page multiples within "
                    "seq_len %d, got %r"
                    % (program.cfg.seq_len, self.buckets))
        self.pool = PagePool(n_pages, self.page_size,
                             program.bytes_per_page())
        self.provenance = dict(provenance) if provenance else None
        self.example_shape = None   # prompts are variable-length tokens
        import jax.numpy as jnp
        names = program.program.param_names
        missing = [n for n in names if n not in params]
        if missing:
            raise MXNetError("params missing %r (MeshProgram layout)"
                             % (missing[:3],))
        self._vals = tuple(jnp.asarray(params[n], jnp.float32)
                           for n in names)
        self._param_bytes = int(sum(4 * v.size for v in self._vals))
        # _lock guards the cache pools (donated through every call) and
        # serializes device dispatch — the ModelRunner._lock discipline
        self._lock = threading.Lock()
        self._prefill_fn, self._decode_fn = program.build_runtime_fns(mesh)
        cache_dtype = jnp.int8 if program.kv_quantized else jnp.float32
        self._ck = jnp.zeros(program.global_cache_shape(n_pages),
                             cache_dtype)
        self._cv = jnp.zeros_like(self._ck)
        if program.kv_quantized:
            # int8 pools carry per-row f32 scale pools beside the codes
            # (docs/precision.md) — threaded through every device call
            self._sk = jnp.ones(program.global_scale_shape(n_pages),
                                jnp.float32)
            self._sv = jnp.ones_like(self._sk)
        else:
            self._sk = self._sv = None
        self._warm_keys = frozenset()
        self.warmed_up = False
        if warmup:
            self.warmup()

    # -- bucket arithmetic -------------------------------------------------
    @property
    def max_prompt_tokens(self):
        return self.buckets[-1]

    @property
    def max_batch(self):
        return self.slots

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise MXNetError("prompt of %d tokens exceeds the largest "
                         "prefill bucket %d" % (n, self.buckets[-1]))

    # -- modeled admission bound (the satellite-6 contract) ----------------
    def admission_hbm_bytes(self):
        """Pages-based modeled HBM this runner pins: weights + the KV
        page pool + one decode step's working set — NOT the
        max-over-buckets full-forward worst case ``ModelRunner`` prices
        for fixed-shape models.  The page pool is the knob: a decode
        model admits at page granularity against the SRV004 cap."""
        cfg = self.program.cfg
        t_max = self.pages_per_seq * self.page_size
        # per-slot decode working set: the gathered K+V run, the
        # attention scores, a few hidden-width residents and the
        # full-vocab logits row — all f32
        step = self.slots * 4 * (
            2 * t_max * cfg.n_heads * cfg.head_dim
            + cfg.n_heads * t_max
            + 4 * cfg.d_model + cfg.d_ff + cfg.vocab_size)
        return self._param_bytes + self.cache_bytes() + step

    def modeled_peak_hbm(self):
        return self.admission_hbm_bytes()

    def cache_bytes(self):
        return self.pool.n_pages * self.pool.bytes_per_page

    # -- execution ---------------------------------------------------------
    def _pad_prompt(self, prompt):
        prompt = _np.asarray(prompt, _np.int32).ravel()
        if prompt.size < 1:
            raise MXNetError("empty prompt")
        bucket = self.bucket_for(prompt.size)
        toks = _np.zeros(bucket, _np.int32)
        toks[:prompt.size] = prompt
        return toks, prompt.size

    def prefill(self, prompt, page_row):
        """Run one prompt through its length bucket, writing K/V into
        ``page_row``'s pages; returns the next-token logits ``(V,)`` as
        numpy.  ``page_row`` is the sequence's full
        ``(pages_per_seq,)`` table row (unallocated tail zeros)."""
        import jax.numpy as jnp
        toks, length = self._pad_prompt(prompt)
        pr = _np.asarray(page_row, _np.int32).ravel()
        row = _np.zeros(self.pages_per_seq, _np.int32)
        row[:pr.size] = pr
        with self._lock:
            if self.program.kv_quantized:
                (logits, self._ck, self._cv, self._sk,
                 self._sv) = self._prefill_fn(
                    self._vals, self._ck, self._cv, self._sk, self._sv,
                    jnp.asarray(row[None]), jnp.asarray(toks[None]),
                    jnp.asarray([length], _np.int32))
            else:
                logits, self._ck, self._cv = self._prefill_fn(
                    self._vals, self._ck, self._cv,
                    jnp.asarray(row[None]), jnp.asarray(toks[None]),
                    jnp.asarray([length], _np.int32))
            return _np.asarray(logits[0])

    def decode_step(self, page_tables, lengths, tokens):
        """One token step over the full slot batch: ``page_tables
        (slots, pages_per_seq)``, ``lengths (slots,)``, ``tokens
        (slots,)`` int32 (idle slots all-zero).  Returns the next-token
        logits ``(slots, V)`` as numpy."""
        import jax.numpy as jnp
        with self._lock:
            if self.program.kv_quantized:
                (logits, self._ck, self._cv, self._sk,
                 self._sv) = self._decode_fn(
                    self._vals, self._ck, self._cv, self._sk, self._sv,
                    jnp.asarray(page_tables, _np.int32),
                    jnp.asarray(lengths, _np.int32),
                    jnp.asarray(tokens, _np.int32))
            else:
                logits, self._ck, self._cv = self._decode_fn(
                    self._vals, self._ck, self._cv,
                    jnp.asarray(page_tables, _np.int32),
                    jnp.asarray(lengths, _np.int32),
                    jnp.asarray(tokens, _np.int32))
            return _np.asarray(logits)

    # -- convenience decodes -----------------------------------------------
    def generate(self, prompt, max_new_tokens, eos_token=None):
        """Standalone greedy decode of ONE prompt through the paged
        cache (allocates from the pool, frees on return).  Not for use
        concurrently with a DecodeBatcher over the same runner — the
        pool has one owner (class docstring)."""
        prompt = _np.asarray(prompt, _np.int32).ravel()
        t_max = self.pages_per_seq * self.page_size
        if prompt.size + max_new_tokens > t_max:
            raise MXNetError(
                "prompt %d + max_new %d exceeds the context length %d"
                % (prompt.size, max_new_tokens, t_max))
        need = self.pool.pages_for(prompt.size + max_new_tokens)
        with self._lock:
            pages = self.pool.alloc(min(need, self.pages_per_seq))
        try:
            row = _np.zeros(self.pages_per_seq, _np.int32)
            row[:len(pages)] = pages
            logits = self.prefill(prompt, pages)
            out = [int(logits.argmax())]
            pt = _np.zeros((self.slots, self.pages_per_seq), _np.int32)
            lengths = _np.zeros(self.slots, _np.int32)
            toks = _np.zeros(self.slots, _np.int32)
            pt[0] = row
            lengths[0] = prompt.size
            toks[0] = out[-1]
            while len(out) < max_new_tokens and \
                    (eos_token is None or out[-1] != eos_token):
                step = self.decode_step(pt, lengths, toks)
                out.append(int(step[0].argmax()))
                lengths[0] += 1
                toks[0] = out[-1]
            return _np.asarray(out, _np.int32)
        finally:
            with self._lock:
                self.pool.free(pages)

    def reference_decode(self, prompt, max_new_tokens, eos_token=None):
        """Sequential NO-cache greedy reference: re-prefills the whole
        growing sequence every step through scratch pages only (zero
        table).  O(T^2) and slow on purpose — the numerics oracle the
        continuous-batching tests compare exact against."""
        seq = list(_np.asarray(prompt, _np.int32).ravel())
        out = []
        while len(out) < max_new_tokens and \
                (eos_token is None or not out or out[-1] != eos_token):
            logits = self.prefill(_np.asarray(seq, _np.int32),
                                  _np.zeros(0, _np.int32))
            nxt = int(logits.argmax())
            out.append(nxt)
            seq.append(nxt)
            if eos_token is not None and nxt == eos_token:
                break
        return _np.asarray(out, _np.int32)

    # -- AOT warmup & the recompile contract -------------------------------
    def warmup(self):
        """Compile the whole ladder now: one scratch prefill per length
        bucket plus one idle decode step, then snapshot the jit-cache
        baseline — the ``ModelRunner.warmup`` contract for two phases."""
        for b in self.buckets:
            self.prefill(_np.zeros(b, _np.int32), _np.zeros(0, _np.int32))
        self.decode_step(
            _np.zeros((self.slots, self.pages_per_seq), _np.int32),
            _np.zeros(self.slots, _np.int32),
            _np.zeros(self.slots, _np.int32))
        self._warm_keys = frozenset(self.jit_cache_keys())
        self.warmed_up = True
        return self._warm_keys

    def jit_cache_keys(self):
        """{(phase, i)} over both jitted programs' cache entries — the
        steady-state proof surface (``Executor._cache_size`` lineage)."""
        keys = set()
        for phase, fn in (("prefill", self._prefill_fn),
                          ("decode", self._decode_fn)):
            keys |= {(phase, i) for i in range(fn._cache_size())}
        return keys

    def jit_cache_size(self):
        return len(self.jit_cache_keys())

    def recompiles_since_warmup(self):
        return len(self.jit_cache_keys() - self._warm_keys)

    def __repr__(self):
        return ("<DecodeRunner slots=%d prefill_buckets=%s pages=%d "
                "page_size=%d kv_dtype=%s>"
                % (self.slots, list(self.buckets), self.pool.n_pages,
                   self.page_size, self.program.kv_dtype))


class DecodeStats(ServingStats):
    """ServingStats plus the token-level decode surface: per-token step
    latency percentiles (overall and per tier), token/step/prefill
    totals, and page-pool occupancy — what the telemetry collector and
    the decode bench serialize."""

    def __init__(self, buckets=()):
        super().__init__(buckets)
        self.tokens_total = 0
        self.steps_total = 0
        self.prefills_total = 0
        self.sequences_done_total = 0
        self._token_ms = deque(maxlen=_WINDOW)
        self._tier_token_ms = {}

    def on_prefill(self, bucket, ms):
        with self._lock:
            self.prefills_total += 1
            self._lat_ms.setdefault(int(bucket),
                                    deque(maxlen=_WINDOW)).append(ms)

    def on_step(self, n_active, step_ms, tiers=()):
        """One decode step: every active sequence got one token at
        ``step_ms`` per-token latency."""
        with self._lock:
            self.steps_total += 1
            self.tokens_total += n_active
            if n_active:
                self._token_ms.append(step_ms)
                for t in tiers:
                    self._tier_token_ms.setdefault(
                        str(t), deque(maxlen=_WINDOW)).append(step_ms)

    def on_sequence_done(self):
        with self._lock:
            self.sequences_done_total += 1

    def token_latency_ms(self, tier=None):
        """(p50, p99) per-token step latency, overall or for one tier."""
        with self._lock:
            if tier is None:
                samples = list(self._token_ms)
            else:
                samples = list(self._tier_token_ms.get(str(tier), ()))
        return percentile(samples, 50), percentile(samples, 99)

    def as_dict(self):
        out = super().as_dict()
        p50, p99 = self.token_latency_ms()
        with self._lock:
            tiers = {}
            for t in sorted(self._tier_token_ms):
                s = list(self._tier_token_ms[t])
                tiers[t] = {"count": len(s),
                            "p50_ms": round(percentile(s, 50), 3),
                            "p99_ms": round(percentile(s, 99), 3)}
            out["decode"] = {
                "tokens_total": self.tokens_total,
                "steps_total": self.steps_total,
                "prefills_total": self.prefills_total,
                "sequences_done_total": self.sequences_done_total,
                "token_p50_ms": round(p50, 3),
                "token_p99_ms": round(p99, 3),
                "tiers": tiers,
            }
        return out


class _DecodeRequest:
    """One sequence in flight: prompt, token budget, SLO coordinates,
    the accumulated greedy tokens and a tiny future.  Orders by
    (tier rank, absolute deadline, arrival) — the ``_Pending`` key."""

    __slots__ = ("prompt", "max_new", "tier_rank", "deadline_ms",
                 "t_deadline", "seq", "t_submit", "on_token", "tokens",
                 "slot", "pages", "cached_len", "_event", "_result",
                 "_exc")

    def __init__(self, prompt, max_new, tier_rank=0, deadline_ms=None,
                 seq=0, on_token=None):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.tier_rank = tier_rank
        self.deadline_ms = deadline_ms
        self.t_submit = time.monotonic()
        self.t_deadline = (self.t_submit + deadline_ms / 1000.0
                           if deadline_ms is not None else None)
        self.seq = seq
        self.on_token = on_token
        self.tokens = []
        self.slot = None
        self.pages = None
        self.cached_len = 0
        self._event = threading.Event()
        self._result = None
        self._exc = None

    @property
    def tier(self):
        return tier_name(self.tier_rank)

    @property
    def tokens_left(self):
        return self.max_new - len(self.tokens)

    def _key(self):
        return (self.tier_rank,
                self.t_deadline if self.t_deadline is not None
                else float("inf"),
                self.seq)

    def __lt__(self, other):
        return self._key() < other._key()

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_exception(self, exc):
        self._exc = exc
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("sequence not decoded within %ss" % timeout)
        if self._exc is not None:
            raise self._exc
        return self._result


class DecodeBatcher:
    """Continuous batching over one :class:`DecodeRunner`.

    One worker thread owns the slot table.  Each iteration it: sweeps
    hopeless queued requests (tokens-remaining arithmetic, below),
    joins queued sequences into free slots while the page pool covers
    their full token budget (strict priority order — a head that does
    not fit blocks lower tiers, deterministically), prefills joiners
    (their first token comes from prefill), runs ONE decode step for
    the active set, appends each slot's greedy token, and retires
    finished sequences — freeing their pages the same step
    (:meth:`schedule_events` logs every join/leave/shed with its step
    ordinal; the determinism tests replay it byte-identical).

    Tokens-remaining admission arithmetic (docs/serving.md):

    - per-token time ``est`` = ``token_time_hint_ms`` when pinned, else
      the EWMA of measured step times (optimistic 0 before any signal);
    - modeled completion of a request at queue ``position`` =
      ``(slot_wait + ahead_tokens // slots + max_new) * est`` where
      ``slot_wait`` is 0 with a free slot else the smallest
      tokens-remaining among active sequences, and ``ahead_tokens`` is
      the summed token budget queued ahead of it;
    - a request whose modeled completion exceeds ``deadline_ms`` is
      shed at admission (``shed_at="admit"``), evicted by rank under a
      full queue (``"evict"``), or swept from the queue when it becomes
      hopeless (``"sweep"``) — the Batcher ladder, in tokens.  Active
      sequences are never shed: once a slot is granted it runs to
      completion (pages stay leased a bounded time by construction).

    ``paused=True`` holds the worker until :meth:`release` — the
    determinism tests submit a whole seeded burst sequentially first,
    so arrival order (and with a pinned hint, every shed decision) is
    reproducible bit-for-bit.
    """

    def __init__(self, runner, max_queue=64, token_time_hint_ms=None,
                 stats=None, model=None, eos_token=None,
                 on_step_success=None, on_step_error=None, paused=False):
        self.runner = runner
        self.max_queue = int(max_queue)
        self.model = model
        self.eos_token = eos_token
        self.token_time_hint_ms = token_time_hint_ms
        self.stats = stats if stats is not None else \
            DecodeStats(runner.buckets)
        self.on_step_success = on_step_success
        self.on_step_error = on_step_error
        self._est_token_ewma_ms = None
        # _cond guards _queue/_slots/_seq/_step_no/_schedule and the
        # runner's page pool bookkeeping; never held across device calls
        self._cond = threading.Condition()
        self._queue = []           # sorted by _DecodeRequest._key()
        self._slots = [None] * runner.slots
        self._seq = 0
        self._step_no = 0
        self._schedule = []
        self._paused = bool(paused)
        # held only around runner calls (prefill + the decode step); the
        # stalled() probe reads _step_started bare, single-writer
        self._runner_lock = threading.Lock()
        self._step_started = None
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-decode-batcher",
                                        daemon=True)
        self._thread.start()

    # -- tokens-remaining admission arithmetic ------------------------------
    @property
    def est_token_ms(self):
        if self.token_time_hint_ms is not None:
            return float(self.token_time_hint_ms)
        return self._est_token_ewma_ms

    def _modeled_completion_ms_locked(self, req, position):
        """Modeled time to FINISH a request at queue ``position`` (class
        docstring arithmetic); 0.0 with no per-token signal yet."""
        est = self.est_token_ms
        if est is None:
            return 0.0
        active = [r for r in self._slots if r is not None]
        if len(active) < len(self._slots):
            slot_wait = 0
        else:
            slot_wait = min(r.tokens_left for r in active)
        ahead = sum(r.max_new for r in self._queue[:position])
        return (slot_wait + ahead // len(self._slots)
                + req.max_new) * est

    def modeled_wait_ms(self):
        """Modeled wait-to-first-token a request submitted now at the
        lowest priority would see (the /stats + Retry-After surface)."""
        with self._cond:
            est = self.est_token_ms
            if est is None:
                return 0.0
            active = [r for r in self._slots if r is not None]
            slot_wait = 0 if len(active) < len(self._slots) \
                else min(r.tokens_left for r in active)
            ahead = sum(r.max_new for r in self._queue)
            return (slot_wait + ahead // len(self._slots)) * est

    def _retry_after_s(self, wait_ms):
        return max(1.0, math.ceil(wait_ms / 1000.0))

    # -- client side -------------------------------------------------------
    @property
    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    @property
    def active_sequences(self):
        with self._cond:
            return sum(1 for r in self._slots if r is not None)

    @property
    def draining(self):
        return self._draining.is_set()

    def stalled(self, threshold_s):
        started = self._step_started
        return started is not None and \
            time.monotonic() - started > float(threshold_s)

    def submit(self, prompt, max_new_tokens=16, tier=DEFAULT_TIER,
               deadline_ms=None, on_token=None):
        """Enqueue one prompt; returns a future-like whose ``result()``
        is the ``(n,)`` int32 array of greedily decoded tokens.

        ``max_new_tokens`` is the token budget the page allocation (and
        the tokens-remaining arithmetic) covers — generation stops
        there or at ``eos_token``.  ``on_token(token_id)`` streams each
        token as it lands (called outside every lock).  Sheds/rejects
        exactly like :class:`~mxnet_tpu.serving.batcher.Batcher`:
        :class:`RequestShed` / :class:`ServerBusy` / :class:`Draining`,
        never blocking the caller."""
        rank = tier_rank(tier)
        if deadline_ms is not None and deadline_ms <= 0:
            raise MXNetError("deadline_ms must be positive, got %r"
                             % (deadline_ms,))
        prompt = _np.asarray(prompt, _np.int32).ravel()
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1, got %r"
                             % (max_new_tokens,))
        if prompt.size + max_new > self.runner.pages_per_seq \
                * self.runner.page_size:
            raise MXNetError(
                "prompt %d + max_new %d exceeds the context length %d"
                % (prompt.size, max_new,
                   self.runner.pages_per_seq * self.runner.page_size))
        self.runner.bucket_for(prompt.size)   # raises on over-long prompt
        victim = None
        with self._cond:
            if self._draining.is_set():
                raise Draining("decode server is draining; "
                               "request rejected")
            req = _DecodeRequest(prompt, max_new, rank, deadline_ms,
                                 self._seq, on_token)
            self._seq += 1
            position = bisect.bisect_left(self._queue, req)
            if deadline_ms is not None:
                done_ms = self._modeled_completion_ms_locked(req, position)
                if done_ms > deadline_ms:
                    self.stats.on_shed(req.tier)
                    self._schedule.append(
                        ("shed-admit", req.seq, self._step_no))
                    raise RequestShed(
                        "modeled completion %.0fms exceeds deadline "
                        "%.0fms (tier=%s, %d tokens, depth=%d); shed at "
                        "admission" % (done_ms, deadline_ms, req.tier,
                                       max_new, len(self._queue)),
                        tier=req.tier,
                        retry_after_s=self._retry_after_s(done_ms),
                        shed_at="admit")
            if len(self._queue) >= self.max_queue:
                if self._queue and req < self._queue[-1]:
                    victim = self._queue.pop()
                    self.stats.on_dequeue(1)
                    self.stats.on_shed(victim.tier)
                    self._schedule.append(
                        ("shed-evict", victim.seq, self._step_no))
                else:
                    self.stats.on_reject()
                    raise ServerBusy(
                        "decode queue full (%d deep); retry later"
                        % self.max_queue) from None
            bisect.insort(self._queue, req)
            self._cond.notify_all()
        if victim is not None:
            victim.set_exception(RequestShed(
                "evicted by a higher-tier arrival under a full queue "
                "(tier=%s)" % victim.tier, tier=victim.tier,
                retry_after_s=self._retry_after_s(self.modeled_wait_ms()),
                shed_at="evict"))
        self.stats.on_submit()
        return req

    def decode(self, prompt, max_new_tokens=16, timeout=60.0,
               tier=DEFAULT_TIER, deadline_ms=None):
        """Blocking convenience: submit + wait for the decoded tokens."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           tier=tier, deadline_ms=deadline_ms
                           ).result(timeout)

    def release(self):
        """Start a ``paused=True`` batcher's worker."""
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def schedule_events(self):
        """The deterministic continuous-batching schedule: a tuple of
        ``(event, request_seq, step_ordinal)`` rows over joins, leaves
        and sheds — what the determinism tests compare byte-identical
        across seeded reruns."""
        with self._cond:
            return tuple(self._schedule)

    # -- worker side -------------------------------------------------------
    def _sweep_hopeless_locked(self):
        if not self._queue:
            return []
        now = time.monotonic()
        shed, keep = [], []
        for pos, req in enumerate(self._queue):
            if req.t_deadline is not None and \
                    now + self._modeled_completion_ms_locked(req, pos) \
                    / 1000.0 > req.t_deadline:
                shed.append(req)
                self._schedule.append(("shed-sweep", req.seq,
                                       self._step_no))
            else:
                keep.append(req)
        if shed:
            self._queue = keep
            self.stats.on_dequeue(len(shed))
            for req in shed:
                self.stats.on_shed(req.tier, swept=True)
        return shed

    def _join_locked(self):
        """Admit queued sequences into free slots in strict priority
        order while the pool covers their FULL token budget; returns the
        joiners (prefill happens outside the lock).  A head that does
        not fit stops admission — no lower-tier bypass, so the schedule
        stays deterministic (class docstring)."""
        pool = self.runner.pool
        joins = []
        while self._queue:
            free = [i for i, r in enumerate(self._slots) if r is None]
            if not free:
                break
            req = self._queue[0]
            need = pool.pages_for(req.prompt.size + req.max_new)
            if need > pool.available:
                break
            self._queue.pop(0)
            self.stats.on_dequeue(1)
            req.pages = pool.alloc(need)
            req.slot = free[0]
            self._slots[req.slot] = req
            self._schedule.append(("join", req.seq, self._step_no))
            joins.append(req)
        return joins

    def _retire_locked(self, req):
        self._slots[req.slot] = None
        self.runner.pool.free(req.pages)
        req.pages = None
        self._schedule.append(("leave", req.seq, self._step_no))
        self.stats.on_sequence_done()

    def _page_row(self, req):
        row = _np.zeros(self.runner.pages_per_seq, _np.int32)
        row[:len(req.pages)] = req.pages
        return row

    def _prefill_joiners(self, joins):
        """Prefill each joiner (outside ``_cond``; the runner serializes
        device calls) — its first greedy token comes from the prefill
        logits.  Returns the sequences already finished (budget of 1 or
        an immediate eos)."""
        finished = []
        for req in joins:
            t0 = time.monotonic()
            self._step_started = t0
            try:
                with self._runner_lock:
                    logits = self.runner.prefill(req.prompt,
                                                 req.pages)
            finally:
                self._step_started = None
            self.stats.on_prefill(self.runner.bucket_for(req.prompt.size),
                                  (time.monotonic() - t0) * 1000.0)
            req.cached_len = int(req.prompt.size)
            tok = int(logits.argmax())
            req.tokens.append(tok)
            if req.on_token is not None:
                try:
                    req.on_token(tok)
                except Exception:
                    pass
            if req.tokens_left == 0 or tok == self.eos_token:
                finished.append(req)
        return finished

    def _run_step(self, active):
        """One decode step for the current active set.  Chaos fires the
        registered ``serving.batch`` site per step; a raise fails every
        active sequence AND frees its pages (no-leak contract)."""
        from ..resilience import chaos as _chaos
        self._step_started = time.monotonic()
        try:
            _chaos.maybe_inject("serving.batch", ctx=active)
            pt = _np.zeros((self.runner.slots, self.runner.pages_per_seq),
                           _np.int32)
            lengths = _np.zeros(self.runner.slots, _np.int32)
            toks = _np.zeros(self.runner.slots, _np.int32)
            for req in active:
                pt[req.slot] = self._page_row(req)
                lengths[req.slot] = req.cached_len
                toks[req.slot] = req.tokens[-1]
            with self._runner_lock:
                logits = self.runner.decode_step(pt, lengths, toks)
            step_ms = (time.monotonic() - self._step_started) * 1000.0
            self._observe_token_ms(step_ms)
            finished = []
            for req in active:
                tok = int(logits[req.slot].argmax())
                req.tokens.append(tok)
                req.cached_len += 1
                if req.on_token is not None:
                    try:
                        req.on_token(tok)
                    except Exception:
                        pass
                if req.tokens_left == 0 or tok == self.eos_token:
                    finished.append(req)
            self.stats.on_step(len(active), step_ms,
                               tiers=[r.tier for r in active])
            self.stats.set_recompiles(
                self.runner.recompiles_since_warmup())
            with self._cond:
                self._step_no += 1
                for req in finished:
                    self._retire_locked(req)
            for req in finished:
                req.set_result(_np.asarray(req.tokens, _np.int32))
            if self.on_step_success is not None:
                try:
                    self.on_step_success()
                except Exception:
                    pass
        except Exception as e:
            # chaos raise or a runner failure: fail every active
            # sequence, free its pages — pages never leak (the chaos
            # reclamation test), the worker keeps serving
            with self._cond:
                self._step_no += 1
                for req in active:
                    if req.pages is not None:
                        self._retire_locked(req)
            for req in active:
                if not req.done():
                    req.set_exception(e)
            self.stats.on_batch(0, len(active), [], error=True,
                                tiers=[r.tier for r in active])
            if self.on_step_error is not None:
                try:
                    self.on_step_error(e)
                except Exception:
                    pass
        finally:
            self._step_started = None

    def _observe_token_ms(self, measured_ms):
        if self._est_token_ewma_ms is None:
            self._est_token_ewma_ms = measured_ms
        else:
            self._est_token_ewma_ms = 0.7 * self._est_token_ewma_ms \
                + 0.3 * measured_ms

    def _fail_prefilled(self, req, exc):
        """A joiner whose prefill raised: retire it and propagate."""
        with self._cond:
            self._retire_locked(req)
        if not req.done():
            req.set_exception(exc)
        if self.on_step_error is not None:
            try:
                self.on_step_error(exc)
            except Exception:
                pass

    def _loop(self):
        while True:
            with self._cond:
                if self._paused:
                    self._cond.wait(timeout=0.05)
                    continue
                shed = self._sweep_hopeless_locked()
                joins = self._join_locked()
                active = [r for r in self._slots if r is not None]
                if not joins and not active and not shed:
                    if self._draining.is_set() and not self._queue:
                        break
                    self._cond.wait(timeout=0.05)
                    continue
            for req in shed:
                req.set_exception(RequestShed(
                    "deadline %.0fms unreachable (modeled completion "
                    "exceeds remaining budget, tier=%s, %d tokens left); "
                    "shed by sweep" % (req.deadline_ms, req.tier,
                                       req.tokens_left),
                    tier=req.tier,
                    retry_after_s=self._retry_after_s(
                        self.modeled_wait_ms()),
                    shed_at="sweep"))
            prefill_done = []
            for req in joins:
                try:
                    prefill_done += self._prefill_joiners([req])
                except Exception as e:
                    self._fail_prefilled(req, e)
            for req in prefill_done:
                with self._cond:
                    self._retire_locked(req)
                req.set_result(_np.asarray(req.tokens, _np.int32))
            with self._cond:
                active = [r for r in self._slots if r is not None]
            if active:
                self._run_step(active)
        self._drained.set()

    # -- fleet surface ------------------------------------------------------
    def swap_runner(self, runner, timeout=30.0):
        raise MXNetError(
            "DecodeBatcher does not hot-swap: live page tables index one "
            "runner's cache pool; drain and re-register instead")

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout=60.0):
        """Graceful shutdown: stop admitting, decode every queued and
        active sequence to completion, join the worker.  Idempotent."""
        with self._cond:
            self._draining.set()
            self._cond.notify_all()
        if not self._drained.wait(timeout):
            raise TimeoutError("decode batcher did not drain within %ss"
                               % timeout)
        self._thread.join(timeout=5.0)
        return True

    def force_drain(self):
        """Hard drain: fail every queued AND active sequence, free all
        pages, mark drained without waiting for a wedged step.  Returns
        the number of sequences failed."""
        with self._cond:
            self._draining.set()
            stuck, self._queue = self._queue, []
            for i, req in enumerate(self._slots):
                if req is not None:
                    stuck.append(req)
                    if req.pages is not None:
                        self.runner.pool.free(req.pages)
                        req.pages = None
                    self._slots[i] = None
            self._cond.notify_all()
        failed = 0
        for req in stuck:
            self.stats.on_dequeue(1)
            req.set_exception(Draining(
                "decode server hit its drain deadline; sequence "
                "not served"))
            failed += 1
        self._drained.set()
        return failed

    close = drain
