"""ModelRunner: a trained model behind fixed padded batch buckets.

Reference posture: TensorFlow ships serving beside training (Abadi et al.,
2016) and MXNet's paper motivates the symbolic executor with deployment;
this runner is the missing piece over our jit caches.  ``jax.jit`` (via
``Executor`` for Modules, ``CachedOp`` for hybridized Gluon blocks)
compiles one program per input signature — unconstrained request sizes
would compile an unbounded program family.  The runner therefore admits
only a fixed bucket ladder (default 1/4/16/64): every request batch is
zero-padded up to the smallest bucket that fits, all buckets are compiled
ahead of time at load (``warmup()``), and the exposed jit-cache key set
lets callers *assert* that steady-state traffic never triggers a new
compile (the BucketingModule idea, pointed at inference).
"""
from __future__ import annotations

import threading

import numpy as _np

from ..base import MXNetError

__all__ = ["ModelRunner", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 4, 16, 64)


class ModelRunner:
    """Bucketed, recompile-free forward over a Module or HybridBlock.

    Parameters
    ----------
    model : Module (bound, params initialized) or HybridBlock (hybridized)
    buckets : ascending batch sizes compiled at load; requests pad up to
        the smallest fitting bucket, larger batches split into max-bucket
        chunks
    example_shape : per-example input shape (no batch dim).  Required for
        Gluon blocks; inferred from ``data_shapes`` for Modules.
    dtype : input dtype (inferred from the Module's data desc when bound)
    lint : run the SRV serving lint over a Module's symbol at load;
        findings at ERROR severity (non-batch-polymorphic graphs) raise
    warmup : compile every bucket now, so the first request is served by
        a cache hit, and snapshot the jit-cache baseline
    hbm_cap_bytes : SRV003 cap on per-bucket modeled peak HBM (default:
        the ``MXTPU_SERVING_HBM_CAP`` env var; 0/unset disables).  The
        modeled per-bucket cost itself is exposed via ``modeled_cost()``
        and the HTTP ``/stats`` endpoint.
    """

    def __init__(self, model, buckets=DEFAULT_BUCKETS, example_shape=None,
                 dtype=None, lint=True, warmup=True, hbm_cap_bytes=None,
                 provenance=None):
        import os
        if hbm_cap_bytes is None:
            hbm_cap_bytes = int(os.environ.get(
                "MXTPU_SERVING_HBM_CAP", "0")) or None
        self.hbm_cap_bytes = hbm_cap_bytes
        # which checkpoint bytes this runner serves: the resilience
        # checkpoint's provenance dict (digest + epoch/step/train_run_id),
        # surfaced through fleet /stats and named by promotion audit
        # records.  None for runners not built from a tracked checkpoint.
        self.provenance = dict(provenance) if provenance else None
        if not buckets:
            raise MXNetError("ModelRunner needs at least one bucket")
        self.buckets = tuple(sorted(int(b) for b in set(buckets)))
        if self.buckets[0] < 1:
            raise MXNetError("buckets must be positive, got %r"
                             % (self.buckets,))
        self._model = model
        self._lock = threading.Lock()
        self._is_module = hasattr(model, "bind") and hasattr(model, "binded")
        if self._is_module:
            if not model.binded or not model.params_initialized:
                raise MXNetError(
                    "ModelRunner needs a bound, initialized Module")
            desc = model.data_shapes[0]
            self._data_name = desc.name
            self.example_shape = tuple(desc.shape[1:]) \
                if example_shape is None else tuple(example_shape)
            self.dtype = dtype or getattr(desc, "dtype", _np.float32)
            if lint:
                self._lint_symbol()
        else:
            if not getattr(model, "_active", False):
                raise MXNetError(
                    "ModelRunner needs a hybridized HybridBlock "
                    "(call block.hybridize()) — an eager block has no jit "
                    "cache to keep warm")
            if example_shape is None:
                raise MXNetError(
                    "example_shape is required for Gluon blocks")
            self._data_name = "data"
            self.example_shape = tuple(example_shape)
            self.dtype = dtype or _np.float32
        self._warm_keys = frozenset()
        self.warmed_up = False
        if warmup:
            self.warmup()

    # -- load-time checks --------------------------------------------------
    def _lint_symbol(self):
        from ..analysis import ERROR, lint_serving, render_text
        shapes = {d.name: d.shape for d in self._model.data_shapes}
        findings = lint_serving(self._model.symbol, data_shapes=shapes,
                                buckets=self.buckets,
                                hbm_cap_bytes=self.hbm_cap_bytes)
        errors = [f for f in findings if f.severity == ERROR]
        if errors:
            raise MXNetError(
                "symbol cannot be served recompile-free:\n%s"
                % render_text(errors))
        if findings:
            import warnings
            warnings.warn("serving lint:\n%s" % render_text(findings))

    def modeled_cost(self):
        """Static per-bucket cost from the mxcost pass (analysis/cost.py):
        ``{bucket: {"flops", "transfer_bytes", "peak_hbm_bytes",
        "bytes_read", "bytes_written"}}``.  Modeled, not measured — live
        on the CPU host with no device attached; serialized into the
        HTTP ``/stats`` payload as ``modeled_cost``.  Empty for Gluon
        blocks (no Symbol to analyze) or untraceable graphs; memoized
        (the symbol is frozen after load)."""
        if getattr(self, "_modeled_cost", None) is not None:
            return self._modeled_cost
        out = {}
        if self._is_module:
            from ..analysis.cost import analyze_symbol
            base = {d.name: tuple(d.shape)
                    for d in self._model.data_shapes}
            for b in self.buckets:
                shapes = {name: (b,) + s[1:] for name, s in base.items()}
                report = analyze_symbol(self._model.symbol, shapes=shapes)
                if report is None:
                    continue
                d = report.as_dict()
                out[int(b)] = {k: d[k] for k in (
                    "flops", "transfer_bytes", "peak_hbm_bytes",
                    "bytes_read", "bytes_written")}
        self._modeled_cost = out
        return out

    def modeled_peak_hbm(self):
        """Worst-case modeled peak HBM over the bucket ladder (bytes) —
        the figure fleet packing sums against the SRV004 cap.  None when
        the cost pass cannot see the model (Gluon blocks have no Symbol);
        such runners need an explicit ``hbm_bytes`` at registration to
        count against the cap."""
        cost = self.modeled_cost()
        if not cost:
            return None
        return max(row["peak_hbm_bytes"] for row in cost.values())

    def admission_hbm_bytes(self):
        """The bound fleet packing charges this runner against the
        SRV004 cap.  For a fixed-shape runner every admitted request
        really can ride the largest bucket's forward, so the
        max-over-buckets worst case IS the right admission figure; the
        decode tier overrides this with its pages-based bound (weights +
        KV page pool + one step) — pricing a decode model by a
        full-context forward per slot was the over-commit bug."""
        return self.modeled_peak_hbm()

    # -- bucket arithmetic -------------------------------------------------
    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        """Smallest bucket that fits ``n`` requests (``n`` capped at the
        max bucket by the chunking in forward_batch)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # -- execution ---------------------------------------------------------
    def _forward_bucket(self, x):
        """Forward one exactly-bucket-sized array; returns numpy output."""
        if self._is_module:
            from .. import io as _io
            from .. import ndarray as nd
            data = [nd.array(x)]
            label = None
            if self._model.label_shapes:
                # keep the label feed's batch axis in lockstep with the
                # data bucket so the traced program family stays one-per-
                # bucket even for symbols bound with label slots
                label = [nd.array(_np.zeros((x.shape[0],) + tuple(d.shape[1:]),
                                            _np.float32))
                         for d in self._model.label_shapes]
            batch = _io.DataBatch(data=data, label=label)
            self._model.forward(batch, is_train=False)
            return self._model.get_outputs()[0].asnumpy()
        from .. import ndarray as nd
        out = self._model(nd.array(x).astype(self.dtype))
        if isinstance(out, (list, tuple)):
            out = out[0]
        return out.asnumpy()

    def forward_batch(self, x):
        """Run ``x`` of shape ``(n,) + example_shape`` through the model,
        padding up to the nearest bucket (splitting above the max bucket),
        and return outputs for exactly the ``n`` real rows."""
        x = _np.ascontiguousarray(x, dtype=_np.dtype(self.dtype))
        if x.shape[1:] != self.example_shape:
            raise MXNetError(
                "request shape %r does not match example_shape %r"
                % (x.shape[1:], self.example_shape))
        n = x.shape[0]
        if n == 0:
            raise MXNetError("empty request batch")
        outs = []
        with self._lock:
            for start in range(0, n, self.max_batch):
                chunk = x[start:start + self.max_batch]
                bucket = self.bucket_for(chunk.shape[0])
                if chunk.shape[0] < bucket:
                    pad = _np.zeros((bucket - chunk.shape[0],)
                                    + self.example_shape, dtype=x.dtype)
                    padded = _np.concatenate([chunk, pad], axis=0)
                else:
                    padded = chunk
                out = self._forward_bucket(padded)
                outs.append(_np.asarray(out)[:chunk.shape[0]])
        return _np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    def predict(self, example):
        """Single-example convenience: ``example_shape`` in, one row out."""
        example = _np.asarray(example)
        return self.forward_batch(example[None])[0]

    # -- AOT warmup & the recompile contract -------------------------------
    def warmup(self):
        """Compile every bucket now (AOT): one zero-batch forward per
        bucket, then snapshot the jit-cache key set.  After this, any
        growth of the set under traffic is a steady-state recompile —
        ``recompiles_since_warmup()`` must stay 0."""
        for b in self.buckets:
            self._forward_bucket(
                _np.zeros((b,) + self.example_shape,
                          dtype=_np.dtype(self.dtype)))
        self._warm_keys = frozenset(self.jit_cache_keys())
        self.warmed_up = True
        return self._warm_keys

    def jit_cache_keys(self):
        return set(self._model.jit_cache_keys())

    def jit_cache_size(self):
        return self._model.jit_cache_size()

    def recompiles_since_warmup(self):
        """Number of jit-cache keys added after warmup — the serving
        contract is that this stays 0 under steady-state traffic."""
        return len(self.jit_cache_keys() - self._warm_keys)

    def __repr__(self):
        kind = "Module" if self._is_module else "HybridBlock"
        return "<ModelRunner %s buckets=%s example=%s>" % (
            kind, list(self.buckets), self.example_shape)
