"""Serving observability: per-bucket latency percentiles, queue depth,
batch-fill ratio and recompile count.

The counters ride :mod:`mxnet_tpu.profiler` ``Domain``/``Counter`` objects,
so when profiling is on (``profiler.set_state('run')``) every queue-depth
change and recompile lands in the same chrome://tracing JSON the rest of
the framework emits; when profiling is off they are plain in-process
numbers with one-bool-check overhead (the reference profiler contract).
``as_dict()`` is the stable surface the HTTP ``/stats`` endpoint and
``bench.py`` serialize.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .. import profiler

__all__ = ["ServingStats", "percentile"]

# latency samples kept per bucket; old samples age out so /stats reflects
# recent traffic, not the whole process lifetime
_WINDOW = 2048


def percentile(samples, q):
    """Nearest-rank percentile of an iterable of floats (no numpy import on
    the request path)."""
    data = sorted(samples)
    if not data:
        return 0.0
    rank = max(0, min(len(data) - 1, int(round(q / 100.0 * (len(data) - 1)))))
    return data[rank]


class ServingStats:
    """Thread-safe serving metrics shared by Batcher/Server/ModelRunner."""

    def __init__(self, buckets=()):
        self._lock = threading.Lock()
        self._domain = profiler.Domain("serving")
        self.queue_depth = self._domain.new_counter("queue_depth", 0)
        self.recompiles = self._domain.new_counter("recompiles", 0)
        self._lat_ms = {int(b): deque(maxlen=_WINDOW) for b in buckets}
        self._fill = deque(maxlen=_WINDOW)
        self._t0 = time.monotonic()
        self.requests_total = 0
        self.rejected_total = 0
        self.batches_total = 0
        self.errors_total = 0

    # -- recording ---------------------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_total += 1
        self.queue_depth.increment()

    def on_reject(self):
        with self._lock:
            self.rejected_total += 1

    def on_dequeue(self, n=1):
        self.queue_depth.decrement(n)

    def on_batch(self, bucket, n_real, latencies_ms, error=False):
        """One executed batch: ``bucket`` padded size, ``n_real`` requests
        in it, per-request end-to-end latencies."""
        with self._lock:
            self.batches_total += 1
            if error:
                self.errors_total += n_real
            if bucket:
                self._fill.append(n_real / float(bucket))
                lat = self._lat_ms.setdefault(int(bucket),
                                              deque(maxlen=_WINDOW))
                lat.extend(latencies_ms)

    def set_recompiles(self, n):
        if n != self.recompiles._value:
            self.recompiles.set_value(n)

    # -- reporting ---------------------------------------------------------
    def latency_ms(self, bucket=None):
        """(p50, p99) over one bucket, or over all buckets when None."""
        with self._lock:
            if bucket is None:
                samples = [s for d in self._lat_ms.values() for s in d]
            else:
                samples = list(self._lat_ms.get(int(bucket), ()))
        return percentile(samples, 50), percentile(samples, 99)

    def batch_fill_ratio(self):
        with self._lock:
            return (sum(self._fill) / len(self._fill)) if self._fill else 0.0

    def as_dict(self):
        p50, p99 = self.latency_ms()
        with self._lock:
            per_bucket = {}
            for b, d in sorted(self._lat_ms.items()):
                samples = list(d)
                per_bucket[str(b)] = {
                    "count": len(samples),
                    "p50_ms": round(percentile(samples, 50), 3),
                    "p99_ms": round(percentile(samples, 99), 3),
                }
            out = {
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "requests_total": self.requests_total,
                "rejected_total": self.rejected_total,
                "batches_total": self.batches_total,
                "errors_total": self.errors_total,
                "queue_depth": self.queue_depth._value,
                "recompiles": self.recompiles._value,
                "p50_ms": round(p50, 3),
                "p99_ms": round(p99, 3),
                "buckets": per_bucket,
            }
        out["batch_fill_ratio"] = round(self.batch_fill_ratio(), 4)
        return out
