"""Serving observability: per-bucket latency percentiles, queue depth,
batch-fill ratio and recompile count.

The counters ride :mod:`mxnet_tpu.profiler` ``Domain``/``Counter`` objects,
so when profiling is on (``profiler.set_state('run')``) every queue-depth
change and recompile lands in the same chrome://tracing JSON the rest of
the framework emits; when profiling is off they are plain in-process
numbers with one-bool-check overhead (the reference profiler contract).
``as_dict()`` is the stable surface the HTTP ``/stats`` endpoint and
``bench.py`` serialize.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .. import profiler

__all__ = ["ServingStats", "percentile"]

# latency samples kept per bucket; old samples age out so /stats reflects
# recent traffic, not the whole process lifetime
_WINDOW = 2048


def percentile(samples, q):
    """Nearest-rank percentile of an iterable of floats (no numpy import on
    the request path)."""
    data = sorted(samples)
    if not data:
        return 0.0
    rank = max(0, min(len(data) - 1, int(round(q / 100.0 * (len(data) - 1)))))
    return data[rank]


class ServingStats:
    """Thread-safe serving metrics shared by Batcher/Server/ModelRunner."""

    def __init__(self, buckets=()):
        self._lock = threading.Lock()
        self._domain = profiler.Domain("serving")
        self.queue_depth = self._domain.new_counter("queue_depth", 0)
        self.recompiles = self._domain.new_counter("recompiles", 0)
        self._lat_ms = {int(b): deque(maxlen=_WINDOW) for b in buckets}
        self._tier_lat_ms = {}          # tier name -> latency deque
        self._shed_by_tier = {}         # tier name -> shed count
        self._fill = deque(maxlen=_WINDOW)
        self._t0 = time.monotonic()
        self.requests_total = 0
        self.rejected_total = 0
        self.batches_total = 0
        self.errors_total = 0
        self.shed_total = 0
        self.swept_total = 0
        self.degraded_total = 0
        self.swaps_total = 0
        self._depth = 0
        self.queue_depth_peak = 0

    # -- recording ---------------------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_total += 1
            self._depth += 1
            if self._depth > self.queue_depth_peak:
                self.queue_depth_peak = self._depth
        self.queue_depth.increment()

    def on_reject(self):
        with self._lock:
            self.rejected_total += 1

    def on_shed(self, tier, swept=False):
        """One request shed by admission control (tier-confined load
        shedding: shed-at-admit, eviction, or the worker sweep)."""
        with self._lock:
            self.shed_total += 1
            if swept:
                self.swept_total += 1
            self._shed_by_tier[str(tier)] = \
                self._shed_by_tier.get(str(tier), 0) + 1

    def on_degraded(self):
        """One request rerouted to the registered cheaper variant."""
        with self._lock:
            self.degraded_total += 1

    def on_swap(self):
        with self._lock:
            self.swaps_total += 1

    def on_dequeue(self, n=1):
        with self._lock:
            self._depth = max(0, self._depth - n)
        self.queue_depth.decrement(n)

    def on_batch(self, bucket, n_real, latencies_ms, error=False, tiers=()):
        """One executed batch: ``bucket`` padded size, ``n_real`` requests
        in it, per-request end-to-end latencies (``tiers`` aligned with
        ``latencies_ms`` when given)."""
        with self._lock:
            self.batches_total += 1
            if error:
                self.errors_total += n_real
            if bucket:
                self._fill.append(n_real / float(bucket))
                lat = self._lat_ms.setdefault(int(bucket),
                                              deque(maxlen=_WINDOW))
                lat.extend(latencies_ms)
                for t, ms in zip(tiers, latencies_ms):
                    self._tier_lat_ms.setdefault(
                        str(t), deque(maxlen=_WINDOW)).append(ms)

    def set_recompiles(self, n):
        if n != self.recompiles._value:
            self.recompiles.set_value(n)

    # -- reporting ---------------------------------------------------------
    def latency_ms(self, bucket=None):
        """(p50, p99) over one bucket, or over all buckets when None."""
        with self._lock:
            if bucket is None:
                samples = [s for d in self._lat_ms.values() for s in d]
            else:
                samples = list(self._lat_ms.get(int(bucket), ()))
        return percentile(samples, 50), percentile(samples, 99)

    def batch_fill_ratio(self):
        with self._lock:
            return (sum(self._fill) / len(self._fill)) if self._fill else 0.0

    def tier_latency_ms(self, tier):
        """(p50, p99) over one tier's served requests."""
        with self._lock:
            samples = list(self._tier_lat_ms.get(str(tier), ()))
        return percentile(samples, 50), percentile(samples, 99)

    def shed_rate(self):
        """Fraction of arriving requests shed by admission control
        (shed / (admitted + shed))."""
        with self._lock:
            arrived = self.requests_total + self.shed_total
            return (self.shed_total / float(arrived)) if arrived else 0.0

    def as_dict(self):
        p50, p99 = self.latency_ms()
        with self._lock:
            per_bucket = {}
            for b, d in sorted(self._lat_ms.items()):
                samples = list(d)
                per_bucket[str(b)] = {
                    "count": len(samples),
                    "p50_ms": round(percentile(samples, 50), 3),
                    "p99_ms": round(percentile(samples, 99), 3),
                }
            per_tier = {}
            for t in sorted(set(self._tier_lat_ms) | set(self._shed_by_tier)):
                samples = list(self._tier_lat_ms.get(t, ()))
                per_tier[t] = {
                    "count": len(samples),
                    "p50_ms": round(percentile(samples, 50), 3),
                    "p99_ms": round(percentile(samples, 99), 3),
                    "shed": self._shed_by_tier.get(t, 0),
                }
            out = {
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "requests_total": self.requests_total,
                "rejected_total": self.rejected_total,
                "batches_total": self.batches_total,
                "errors_total": self.errors_total,
                "shed_total": self.shed_total,
                "swept_total": self.swept_total,
                "degraded_total": self.degraded_total,
                "swaps_total": self.swaps_total,
                "queue_depth": self.queue_depth._value,
                "queue_depth_peak": self.queue_depth_peak,
                "recompiles": self.recompiles._value,
                "p50_ms": round(p50, 3),
                "p99_ms": round(p99, 3),
                "buckets": per_bucket,
                "tiers": per_tier,
            }
        out["batch_fill_ratio"] = round(self.batch_fill_ratio(), 4)
        out["shed_rate"] = round(self.shed_rate(), 4)
        return out
