"""ModelFleet: N named models behind one endpoint, overload-proof.

The serving tier's answer to ROADMAP item 3 ("millions of users"): one
process hosts many :class:`~mxnet_tpu.serving.runner.ModelRunner`\\ s, each
behind its own deadline-aware :class:`~mxnet_tpu.serving.batcher.Batcher`,
with the failure modes of a production fleet handled explicitly —

- **HBM-aware packing (static admission control)**: ``register()`` sums
  the *modeled* peak HBM of every hosted model (the mxcost pass behind
  ``ModelRunner.modeled_cost()``, PR-4 discipline) against the SRV003/4
  cap; an over-cap registration is refused *at load time* with the
  modeled numbers in the error — packing is a solved static problem, not
  a runtime OOM.
- **SLO-tiered routing**: ``submit(example, model=, tier=, deadline_ms=)``
  routes by name; the per-model batcher coalesces deadline-aware and
  sheds deterministically, lowest tier first, before the queue collapses.
- **per-model circuit breaker**: repeated runner failures trip the
  model's :class:`CircuitBreaker` (open durations from
  ``resilience/backoff.py``'s :class:`BackoffPolicy`); while open, traffic
  fails fast (or degrades, below) instead of feeding a sick model, one
  half-open probe window at a time.
- **graceful degradation**: a model registered with ``fallback=`` (the
  int8 quantized variant is the intended citizen — ``tools/serve.py
  --model name=prefix:int8``) absorbs overflow: requests the primary
  sheds (or refuses with an open breaker) are rerouted to the cheaper
  variant instead of being dropped.
- **hot swap under drain**: ``swap()`` replaces a model's runner after
  the in-flight batch completes; queued requests are served by the
  replacement — zero failed in-flight requests, with the blip measured.

Chaos probe sites (``resilience/chaos.py``): ``serving.route`` fires per
routed request (count = request ordinal, ctx = (model, tier)) and
``serving.swap`` per swap (ctx = model name) — the overload/degradation
story is tested by deterministic fault injection, not by prod incidents.
"""
from __future__ import annotations

import math
import threading
import time

from ..base import MXNetError
from ..resilience.backoff import BackoffPolicy
from .batcher import Batcher, DEFAULT_TIER, RequestShed, ServerBusy
from .stats import ServingStats

__all__ = ["ModelFleet", "CircuitBreaker", "BreakerOpen", "UnknownModel"]


class BreakerOpen(MXNetError):
    """The model's circuit breaker is open — fail fast (HTTP 503 with
    ``Retry-After`` = ``retry_after_s``)."""

    def __init__(self, message, model=None, retry_after_s=1.0):
        super().__init__(message)
        self.model = model
        self.retry_after_s = float(retry_after_s)


class UnknownModel(MXNetError):
    """Routing key names no registered model (HTTP 404)."""


class CircuitBreaker:
    """Per-model circuit breaker: closed -> open -> half-open -> closed.

    ``failure_threshold`` consecutive batch failures trip it open; the
    open duration is ``policy.delay(trip_count)`` (exponential, from the
    shared :class:`BackoffPolicy` — a repeatedly-sick model backs off
    harder).  After the open window one probe window is allowed
    (half-open): a success closes the breaker and resets the trip count,
    a failure re-opens it with the next backoff delay.  Thread-safe;
    all timing on ``time.monotonic()``.
    """

    def __init__(self, failure_threshold=3, policy=None):
        self.failure_threshold = int(failure_threshold)
        if self.failure_threshold < 1:
            raise MXNetError("failure_threshold must be >= 1")
        # jitter=0: a single server gains nothing from desynchronizing
        # against itself, and deterministic open windows are what the
        # chaos tests replay
        self.policy = policy if policy is not None else BackoffPolicy(
            base_s=0.5, factor=2.0, max_delay_s=30.0, jitter=0.0)
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._trips = 0
        self._open_until = 0.0

    @property
    def state(self):
        with self._lock:
            return self._state_locked()

    def _state_locked(self):
        if self._state == "open" and \
                time.monotonic() >= self._open_until:
            self._state = "half_open"
        return self._state

    def allow(self):
        """May traffic flow?  True while closed or half-open (the probe
        window); False while the open window runs."""
        with self._lock:
            return self._state_locked() != "open"

    def retry_after_s(self):
        with self._lock:
            if self._state_locked() != "open":
                return 0.0
            return max(0.0, self._open_until - time.monotonic())

    def record_success(self):
        with self._lock:
            self._consecutive = 0
            if self._state_locked() == "half_open":
                self._state = "closed"
                self._trips = 0

    def record_failure(self):
        with self._lock:
            state = self._state_locked()
            if state == "half_open":
                self._trip_locked()
                return
            self._consecutive += 1
            if state == "closed" and \
                    self._consecutive >= self.failure_threshold:
                self._trip_locked()

    def _trip_locked(self):
        self._state = "open"
        self._open_until = time.monotonic() + \
            self.policy.delay(min(self._trips, self.policy.max_retries))
        self._trips += 1
        self._consecutive = 0

    def reset(self):
        """Back to pristine closed (wired to hot swap: a fresh runner
        deserves a fresh failure budget)."""
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._trips = 0
            self._open_until = 0.0

    def __repr__(self):
        return "<CircuitBreaker %s trips=%d>" % (self.state, self._trips)


class _Entry:
    """One hosted model: runner (behind its batcher), breaker, packing
    bytes, fallback route, declared SLOs, swap bookkeeping."""

    __slots__ = ("name", "batcher", "breaker", "hbm_bytes", "fallback",
                 "tier_slos", "last_swap_blip_ms")

    def __init__(self, name, batcher, breaker, hbm_bytes, fallback,
                 tier_slos):
        self.name = name
        self.batcher = batcher
        self.breaker = breaker
        self.hbm_bytes = hbm_bytes
        self.fallback = fallback
        self.tier_slos = dict(tier_slos or {})
        self.last_swap_blip_ms = None

    @property
    def runner(self):
        return self.batcher.runner


class ModelFleet:
    """N named ModelRunners behind one routing surface.

    Parameters
    ----------
    hbm_cap_bytes : summed-modeled-HBM cap for packing (default: the
        ``MXTPU_SERVING_HBM_CAP`` env var; 0/unset disables).  Checked
        statically at every ``register()`` (SRV004).
    stall_threshold_s : a model whose in-flight batch exceeds this is
        reported unready (``/readyz``) while the process stays live.
    batch_timeout_ms / max_queue : per-model Batcher defaults
        (overridable per ``register``).
    """

    def __init__(self, hbm_cap_bytes=None, stall_threshold_s=30.0,
                 batch_timeout_ms=2.0, max_queue=256):
        import os
        if hbm_cap_bytes is None:
            hbm_cap_bytes = int(os.environ.get(
                "MXTPU_SERVING_HBM_CAP", "0")) or None
        self.hbm_cap_bytes = hbm_cap_bytes
        self.stall_threshold_s = float(stall_threshold_s)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._entries = {}          # name -> _Entry, registration order
        self._default = None
        self._route_seq = 0
        # one pane of glass: per-model serving stats + breaker state +
        # the packing ledger become mxtpu_serving_* gauges at every
        # telemetry scrape (weakly held — a dropped fleet disappears)
        from .. import telemetry as _tele
        _tele.registry().register_collector(self._metrics_samples,
                                            name="serving-fleet")

    _BREAKER_STATE_ENUM = {"closed": 0, "open": 1, "half_open": 2}

    def _metrics_samples(self):
        samples = [
            ("mxtpu_serving_modeled_hbm_total_bytes", {},
             self.modeled_hbm_total()),
            ("mxtpu_serving_hbm_cap_bytes", {}, self.hbm_cap_bytes or 0),
        ]
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            labels = {"model": e.name}
            st = e.batcher.stats
            samples.append(("mxtpu_serving_breaker_state", labels,
                            self._BREAKER_STATE_ENUM.get(e.breaker.state,
                                                         -1)))
            samples.append(("mxtpu_serving_queue_depth", labels,
                            e.batcher.queue_depth))
            for key in ("requests_total", "rejected_total", "errors_total",
                        "shed_total", "degraded_total", "swaps_total",
                        "batches_total", "queue_depth_peak"):
                samples.append(("mxtpu_serving_" + key, labels,
                                getattr(st, key)))
            p50, p99 = st.latency_ms()
            samples.append(("mxtpu_serving_latency_p50_ms", labels, p50))
            samples.append(("mxtpu_serving_latency_p99_ms", labels, p99))
            for tier in ("gold", "silver", "bronze"):
                tp50, tp99 = st.tier_latency_ms(tier)
                tl = dict(labels, tier=tier)
                samples.append(("mxtpu_serving_tier_p50_ms", tl, tp50))
                samples.append(("mxtpu_serving_tier_p99_ms", tl, tp99))
        return samples

    # -- registration: admission control as a static problem ---------------
    def models(self):
        with self._lock:
            return list(self._entries)

    @property
    def default_model(self):
        return self._default

    def entry(self, name=None):
        with self._lock:
            key = name if name is not None else self._default
            try:
                return self._entries[key]
            except KeyError:
                raise UnknownModel(
                    "no model %r registered (have: %s)"
                    % (key, sorted(self._entries) or "none")) from None

    def runner(self, name=None):
        return self.entry(name).runner

    def batcher(self, name=None):
        return self.entry(name).batcher

    @staticmethod
    def _modeled_hbm(runner, hbm_bytes=None):
        if hbm_bytes is not None:
            return int(hbm_bytes)
        return runner.modeled_peak_hbm()

    def register(self, name, runner, fallback=None, hbm_bytes=None,
                 max_batch=None, batch_timeout_ms=None, max_queue=None,
                 service_time_hint_ms=None, breaker=None, tier_slos=None):
        """Host ``runner`` as ``name``.  Refused (``MXNetError`` carrying
        the SRV004 finding with the modeled per-model numbers) when the
        fleet's summed modeled peak HBM would exceed ``hbm_cap_bytes`` —
        over-commit is caught at registration, not at the first OOM.

        ``hbm_bytes`` overrides the modeled figure for runners the cost
        pass cannot see (Gluon blocks have no Symbol; their modeled HBM
        is None and only the modeled models count against the cap).
        ``fallback`` names the cheaper variant (registered before or
        after) that absorbs this model's overflow; ``tier_slos`` is the
        declared per-tier p99 budget (ms) surfaced in stats.
        """
        name = str(name)
        candidate = self._modeled_hbm(runner, hbm_bytes)
        with self._lock:
            if name in self._entries:
                raise MXNetError("model %r already registered; use swap()"
                                 % name)
            if self.hbm_cap_bytes:
                from ..analysis.serving_lint import lint_fleet_hbm
                packing = {e.name: e.hbm_bytes
                           for e in self._entries.values()}
                packing[name] = candidate
                findings = lint_fleet_hbm(packing, self.hbm_cap_bytes)
                if findings:
                    from ..analysis import render_text
                    raise MXNetError(
                        "fleet registration refused — modeled HBM over "
                        "cap:\n%s" % render_text(findings))
            breaker = breaker if breaker is not None else CircuitBreaker()
            batcher = Batcher(
                runner, max_batch=max_batch,
                batch_timeout_ms=self.batch_timeout_ms
                if batch_timeout_ms is None else batch_timeout_ms,
                max_queue=self.max_queue if max_queue is None
                else max_queue,
                stats=ServingStats(runner.buckets),
                service_time_hint_ms=service_time_hint_ms,
                on_batch_success=breaker.record_success,
                on_batch_error=lambda exc: breaker.record_failure(),
                model=name)
            entry = _Entry(name, batcher, breaker, candidate, fallback,
                           tier_slos)
            self._entries[name] = entry
            if self._default is None:
                self._default = name
        return entry

    def modeled_hbm_total(self):
        """Summed modeled peak HBM over registered models (None-modeled
        runners excluded) — the packing ledger /stats exposes."""
        with self._lock:
            return sum(e.hbm_bytes for e in self._entries.values()
                       if e.hbm_bytes)

    # -- routing -----------------------------------------------------------
    def submit(self, example, model=None, tier=DEFAULT_TIER,
               deadline_ms=None):
        """Route one example: returns a future-like with ``.result()``.

        Overload ladder: an open breaker or a shed/full-queue refusal on
        the primary reroutes to its registered ``fallback`` (degraded
        mode) when that variant is warm and closed; only when the
        fallback also refuses does the caller see the original
        :class:`RequestShed` / :class:`BreakerOpen` / :class:`ServerBusy`.
        """
        from ..resilience import chaos as _chaos
        entry = self.entry(model)
        with self._lock:
            self._route_seq += 1
            seq = self._route_seq
        _chaos.maybe_inject("serving.route", count=seq,
                            ctx=(entry.name, tier))
        self._check_shape(entry, example)
        return self._submit_entry(entry, example, tier, deadline_ms,
                                  allow_fallback=True)

    def _check_shape(self, entry, example):
        import numpy as _np
        shape = _np.asarray(example).shape
        want = tuple(entry.runner.example_shape)
        if tuple(shape) != want:
            raise MXNetError(
                "example shape %r does not match model %r example_shape "
                "%r" % (tuple(shape), entry.name, want))

    def _fallback_entry(self, entry):
        if not entry.fallback:
            return None
        with self._lock:
            fb = self._entries.get(entry.fallback)
        if fb is None or not getattr(fb.runner, "warmed_up", False):
            return None
        if not fb.breaker.allow() or fb.batcher.draining:
            return None
        return fb

    def _submit_entry(self, entry, example, tier, deadline_ms,
                      allow_fallback):
        if not entry.breaker.allow():
            fb = self._fallback_entry(entry) if allow_fallback else None
            if fb is not None:
                entry.batcher.stats.on_degraded()
                return self._submit_entry(fb, example, tier, deadline_ms,
                                          allow_fallback=False)
            raise BreakerOpen(
                "model %r breaker is open (%d consecutive batch "
                "failures tripped it); retry after %.1fs"
                % (entry.name, entry.breaker.failure_threshold,
                   entry.breaker.retry_after_s()),
                model=entry.name,
                retry_after_s=max(1.0, math.ceil(
                    entry.breaker.retry_after_s())))
        try:
            return entry.batcher.submit(example, tier=tier,
                                        deadline_ms=deadline_ms,
                                        model=entry.name)
        except (RequestShed, ServerBusy):
            fb = self._fallback_entry(entry) if allow_fallback else None
            if fb is None:
                raise
            entry.batcher.stats.on_degraded()
            return self._submit_entry(fb, example, tier, deadline_ms,
                                      allow_fallback=False)

    def infer(self, example, model=None, tier=DEFAULT_TIER,
              deadline_ms=None, timeout=30.0):
        """Blocking convenience: route + wait for the result row."""
        return self.submit(example, model=model, tier=tier,
                           deadline_ms=deadline_ms).result(timeout)

    # -- hot swap ----------------------------------------------------------
    def swap(self, name, runner, warmup=True, timeout=30.0):
        """Replace model ``name``'s runner under drain of its in-flight
        batch: the new runner is warmed first (nothing is routed to a
        cold bucket ladder), the swap waits for the executing batch, and
        queued requests are served by the replacement — zero failed
        in-flight requests.  The breaker resets (a fresh runner deserves
        a fresh failure budget).  Returns the previous runner; the blip
        (ms the swap waited on the in-flight batch) lands in
        ``stats_dict()``."""
        from ..resilience import chaos as _chaos
        entry = self.entry(name)
        _chaos.maybe_inject("serving.swap", ctx=entry.name)
        if warmup and not getattr(runner, "warmed_up", False):
            runner.warmup()
        t0 = time.monotonic()
        old = entry.batcher.swap_runner(runner, timeout=timeout)
        entry.last_swap_blip_ms = (time.monotonic() - t0) * 1000.0
        entry.breaker.reset()
        return old

    # -- readiness ---------------------------------------------------------
    def unready(self):
        """{model: reason} for every model not currently routable:
        ``warming`` (bucket ladder not compiled), ``breaker_open`` /
        ``breaker_half_open`` (tripped on repeated failures), ``stalled``
        (in-flight batch exceeded ``stall_threshold_s``), ``draining``.
        Empty dict == the fleet is ready (the /readyz contract)."""
        out = {}
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if not getattr(e.runner, "warmed_up", False):
                out[e.name] = "warming"
            elif e.breaker.state != "closed":
                out[e.name] = "breaker_%s" % e.breaker.state
            elif e.batcher.stalled(self.stall_threshold_s):
                out[e.name] = "stalled"
            elif e.batcher.draining:
                out[e.name] = "draining"
        return out

    @property
    def ready(self):
        return not self.unready()

    @property
    def draining(self):
        with self._lock:
            entries = list(self._entries.values())
        return any(e.batcher.draining for e in entries)

    # -- observability -----------------------------------------------------
    def stats_dict(self):
        """Per-model stats + the fleet packing/routing ledger."""
        with self._lock:
            entries = list(self._entries.values())
            cap = self.hbm_cap_bytes
        models = {}
        for e in entries:
            d = e.batcher.stats.as_dict()
            d["breaker"] = e.breaker.state
            d["fallback"] = e.fallback
            d["tier_slos_ms"] = dict(e.tier_slos)
            d["modeled_peak_hbm_bytes"] = e.hbm_bytes
            d["queue_depth"] = e.batcher.queue_depth
            d["modeled_wait_ms"] = round(e.batcher.modeled_wait_ms(), 3)
            d["recompiles"] = e.runner.recompiles_since_warmup()
            d["buckets_configured"] = list(e.runner.buckets)
            if e.last_swap_blip_ms is not None:
                d["last_swap_blip_ms"] = round(e.last_swap_blip_ms, 3)
            models[e.name] = d
        return {
            "models": models,
            "default_model": self._default,
            "hbm_cap_bytes": cap,
            "modeled_hbm_total_bytes": self.modeled_hbm_total(),
            "unready": self.unready(),
        }

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout=60.0):
        """Drain every model's batcher against one shared deadline.
        Raises ``TimeoutError`` (after attempting all) when any batcher
        missed it — callers holding a hard deadline follow up with
        :meth:`force_drain`."""
        deadline = time.monotonic() + float(timeout)
        late = []
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            try:
                e.batcher.drain(timeout=max(0.05,
                                            deadline - time.monotonic()))
            except TimeoutError:
                late.append(e.name)
        if late:
            raise TimeoutError("fleet did not drain within %ss "
                               "(stuck: %s)" % (timeout, late))
        return True

    def force_drain(self):
        with self._lock:
            entries = list(self._entries.values())
        return sum(e.batcher.force_drain() for e in entries)

    def __repr__(self):
        return "<ModelFleet %s default=%r>" % (self.models(),
                                               self._default)
