"""ModelFleet: N named models behind one endpoint, overload-proof.

The serving tier's answer to ROADMAP item 3 ("millions of users"): one
process hosts many :class:`~mxnet_tpu.serving.runner.ModelRunner`\\ s, each
behind its own deadline-aware :class:`~mxnet_tpu.serving.batcher.Batcher`,
with the failure modes of a production fleet handled explicitly —

- **HBM-aware packing (static admission control)**: ``register()`` sums
  the *modeled* peak HBM of every hosted model (the mxcost pass behind
  ``ModelRunner.modeled_cost()``, PR-4 discipline) against the SRV003/4
  cap; an over-cap registration is refused *at load time* with the
  modeled numbers in the error — packing is a solved static problem, not
  a runtime OOM.
- **SLO-tiered routing**: ``submit(example, model=, tier=, deadline_ms=)``
  routes by name; the per-model batcher coalesces deadline-aware and
  sheds deterministically, lowest tier first, before the queue collapses.
- **per-model circuit breaker**: repeated runner failures trip the
  model's :class:`CircuitBreaker` (open durations from
  ``resilience/backoff.py``'s :class:`BackoffPolicy`); while open, traffic
  fails fast (or degrades, below) instead of feeding a sick model, one
  half-open probe window at a time.
- **graceful degradation**: a model registered with ``fallback=`` (the
  int8 quantized variant is the intended citizen — ``tools/serve.py
  --model name=prefix:int8``) absorbs overflow: requests the primary
  sheds (or refuses with an open breaker) are rerouted to the cheaper
  variant instead of being dropped.
- **hot swap under drain**: ``swap()`` replaces a model's runner after
  the in-flight batch completes; queued requests are served by the
  replacement — zero failed in-flight requests, with the blip measured.
- **deterministic canary traffic split** (ISSUE 12): ``set_canary()``
  arms a :class:`CanarySplit` on a model — a seeded hash of each
  request id decides incumbent vs canary (pure function: byte-identical
  request sets across reruns, unaffected by hot swaps), the canary
  fraction ramps along a *pinned schedule* advanced explicitly by the
  promotion controller (never by wall clock), and attribution is
  per-variant: a canary refusal (shed / full queue / open breaker)
  falls back to the incumbent with the degrade billed to the CANARY's
  stats — canary trouble never dirties the incumbent's ledger.

Chaos probe sites (``resilience/chaos.py``): ``serving.route`` fires per
routed request (count = request ordinal, ctx = (model, tier)) and
``serving.swap`` per swap (ctx = model name) — the overload/degradation
story is tested by deterministic fault injection, not by prod incidents.
"""
from __future__ import annotations

import hashlib
import math
import threading
import time

from ..base import MXNetError
from ..resilience.backoff import BackoffPolicy
from .batcher import Batcher, DEFAULT_TIER, RequestShed, ServerBusy
from .stats import ServingStats

__all__ = ["ModelFleet", "CircuitBreaker", "BreakerOpen", "UnknownModel",
           "CanarySplit", "DEFAULT_CANARY_SCHEDULE"]

# the pinned default ramp: 1% -> 5% -> 25% of traffic.  Stages advance
# only via CanarySplit.advance() (the promotion controller's explicit
# decision), never on a timer — rerunning a seeded workload replays the
# exact same ramp at the exact same request ordinals.
DEFAULT_CANARY_SCHEDULE = (0.01, 0.05, 0.25)


class CanarySplit:
    """Deterministic canary routing state for one model.

    ``routes_to_canary(request_id)`` is a pure function of
    ``(seed, request_id, fraction)``: sha256 of ``"<seed>:<id>"`` mapped
    onto [0, 1) and compared against the current stage's fraction.  Two
    reruns with the same seed and request-id stream therefore split into
    byte-identical canary/incumbent request sets — at 1%, 5% and 25%,
    through hot swaps (the hash never looks at the runner) and across
    processes.  Thread-safe; the only mutable state is the stage index
    and the per-variant routed counters.
    """

    __slots__ = ("canary", "schedule", "seed", "_stage", "_lock",
                 "routed_canary", "routed_incumbent")

    def __init__(self, canary, schedule=DEFAULT_CANARY_SCHEDULE, seed=0):
        schedule = tuple(float(f) for f in schedule)
        if not schedule or not all(0.0 < f <= 1.0 for f in schedule):
            raise MXNetError(
                "canary schedule must be non-empty fractions in (0, 1], "
                "got %r" % (schedule,))
        if list(schedule) != sorted(schedule):
            raise MXNetError(
                "canary schedule must ramp monotonically, got %r"
                % (schedule,))
        self.canary = str(canary)
        self.schedule = schedule
        self.seed = int(seed)
        self._stage = 0
        self._lock = threading.Lock()
        self.routed_canary = 0
        self.routed_incumbent = 0

    @property
    def stage(self):
        with self._lock:
            return self._stage

    @property
    def fraction(self):
        with self._lock:
            return self.schedule[self._stage]

    @property
    def final_stage(self):
        with self._lock:
            return self._stage == len(self.schedule) - 1

    def advance(self):
        """Step the pinned ramp (controller decision); returns the new
        fraction.  Idempotent at the last stage."""
        with self._lock:
            if self._stage < len(self.schedule) - 1:
                self._stage += 1
            return self.schedule[self._stage]

    def routes_to_canary(self, request_id):
        """True when ``request_id`` falls in the canary slice at the
        current fraction.  Stable under ramp-up: a request id routed to
        the canary at 1% is still canary at 5% and 25% (the hash point
        does not move; only the threshold does)."""
        h = hashlib.sha256(
            ("%d:%s" % (self.seed, request_id)).encode()).digest()
        point = int.from_bytes(h[:8], "big") / float(1 << 64)
        return point < self.fraction

    def record_route(self, to_canary):
        with self._lock:
            if to_canary:
                self.routed_canary += 1
            else:
                self.routed_incumbent += 1

    def state_dict(self):
        with self._lock:
            return {
                "canary": self.canary,
                "fraction": self.schedule[self._stage],
                "stage": self._stage,
                "schedule": list(self.schedule),
                "seed": self.seed,
                "final_stage": self._stage == len(self.schedule) - 1,
                "routed_canary": self.routed_canary,
                "routed_incumbent": self.routed_incumbent,
            }

    def __repr__(self):
        # one acquisition, raw fields: the fraction property takes the
        # same non-reentrant lock
        with self._lock:
            stage = self._stage
            fraction = self.schedule[stage]
        return "<CanarySplit ->%s %.3g stage=%d/%d>" % (
            self.canary, fraction, stage, len(self.schedule))


class BreakerOpen(MXNetError):
    """The model's circuit breaker is open — fail fast (HTTP 503 with
    ``Retry-After`` = ``retry_after_s``)."""

    def __init__(self, message, model=None, retry_after_s=1.0):
        super().__init__(message)
        self.model = model
        self.retry_after_s = float(retry_after_s)


class UnknownModel(MXNetError):
    """Routing key names no registered model (HTTP 404)."""


class CircuitBreaker:
    """Per-model circuit breaker: closed -> open -> half-open -> closed.

    ``failure_threshold`` consecutive batch failures trip it open; the
    open duration is ``policy.delay(trip_count)`` (exponential, from the
    shared :class:`BackoffPolicy` — a repeatedly-sick model backs off
    harder).  After the open window one probe window is allowed
    (half-open): a success closes the breaker and resets the trip count,
    a failure re-opens it with the next backoff delay.  Thread-safe;
    all timing on ``time.monotonic()``.
    """

    def __init__(self, failure_threshold=3, policy=None):
        self.failure_threshold = int(failure_threshold)
        if self.failure_threshold < 1:
            raise MXNetError("failure_threshold must be >= 1")
        # jitter=0: a single server gains nothing from desynchronizing
        # against itself, and deterministic open windows are what the
        # chaos tests replay
        self.policy = policy if policy is not None else BackoffPolicy(
            base_s=0.5, factor=2.0, max_delay_s=30.0, jitter=0.0)
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._trips = 0
        self._open_until = 0.0

    @property
    def state(self):
        with self._lock:
            return self._state_locked()

    def _state_locked(self):
        if self._state == "open" and \
                time.monotonic() >= self._open_until:
            self._state = "half_open"
        return self._state

    def allow(self):
        """May traffic flow?  True while closed or half-open (the probe
        window); False while the open window runs."""
        with self._lock:
            return self._state_locked() != "open"

    def retry_after_s(self):
        with self._lock:
            if self._state_locked() != "open":
                return 0.0
            return max(0.0, self._open_until - time.monotonic())

    def record_success(self):
        with self._lock:
            self._consecutive = 0
            if self._state_locked() == "half_open":
                self._state = "closed"
                self._trips = 0

    def record_failure(self):
        with self._lock:
            state = self._state_locked()
            if state == "half_open":
                self._trip_locked()
                return
            self._consecutive += 1
            if state == "closed" and \
                    self._consecutive >= self.failure_threshold:
                self._trip_locked()

    def _trip_locked(self):
        self._state = "open"
        self._open_until = time.monotonic() + \
            self.policy.delay(min(self._trips, self.policy.max_retries))
        self._trips += 1
        self._consecutive = 0

    def reset(self):
        """Back to pristine closed (wired to hot swap: a fresh runner
        deserves a fresh failure budget)."""
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._trips = 0
            self._open_until = 0.0

    def __repr__(self):
        # one acquisition, raw state: the state property takes the same
        # non-reentrant lock
        with self._lock:
            return "<CircuitBreaker %s trips=%d>" % (
                self._state_locked(), self._trips)


class _Entry:
    """One hosted model: runner (behind its batcher), breaker, packing
    bytes, fallback route, declared SLOs, swap bookkeeping, and — when a
    traffic split is armed — the canary wiring (``canary`` on the
    incumbent, ``canary_of`` on the canary variant)."""

    __slots__ = ("name", "batcher", "breaker", "hbm_bytes", "fallback",
                 "tier_slos", "last_swap_blip_ms", "canary", "canary_of")

    def __init__(self, name, batcher, breaker, hbm_bytes, fallback,
                 tier_slos):
        self.name = name
        self.batcher = batcher
        self.breaker = breaker
        self.hbm_bytes = hbm_bytes
        self.fallback = fallback
        self.tier_slos = dict(tier_slos or {})
        self.last_swap_blip_ms = None
        self.canary = None         # CanarySplit while this model ramps one
        self.canary_of = None      # incumbent name while serving as canary

    @property
    def runner(self):
        return self.batcher.runner


class ModelFleet:
    """N named ModelRunners behind one routing surface.

    Parameters
    ----------
    hbm_cap_bytes : summed-modeled-HBM cap for packing (default: the
        ``MXTPU_SERVING_HBM_CAP`` env var; 0/unset disables).  Checked
        statically at every ``register()`` (SRV004).
    stall_threshold_s : a model whose in-flight batch exceeds this is
        reported unready (``/readyz``) while the process stays live.
    batch_timeout_ms / max_queue : per-model Batcher defaults
        (overridable per ``register``).
    """

    def __init__(self, hbm_cap_bytes=None, stall_threshold_s=30.0,
                 batch_timeout_ms=2.0, max_queue=256):
        import os
        if hbm_cap_bytes is None:
            hbm_cap_bytes = int(os.environ.get(
                "MXTPU_SERVING_HBM_CAP", "0")) or None
        self.hbm_cap_bytes = hbm_cap_bytes
        self.stall_threshold_s = float(stall_threshold_s)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._entries = {}          # name -> _Entry, registration order
        self._default = None
        self._route_seq = 0
        # one pane of glass: per-model serving stats + breaker state +
        # the packing ledger become mxtpu_serving_* gauges at every
        # telemetry scrape (weakly held — a dropped fleet disappears)
        from .. import telemetry as _tele
        _tele.registry().register_collector(self._metrics_samples,
                                            name="serving-fleet")

    _BREAKER_STATE_ENUM = {"closed": 0, "open": 1, "half_open": 2}

    def _metrics_samples(self):
        samples = [
            ("mxtpu_serving_modeled_hbm_total_bytes", {},
             self.modeled_hbm_total()),
            ("mxtpu_serving_hbm_cap_bytes", {}, self.hbm_cap_bytes or 0),
        ]
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            labels = {"model": e.name}
            # per-VARIANT attribution: a canary's counters carry the
            # incumbent's name as `canary_of`, so dashboards (and the
            # promotion controller) can tell canary shed/degrade/breaker
            # trips from incumbent ones without string surgery
            if e.canary_of:
                labels["canary_of"] = e.canary_of
            if e.canary is not None:
                split = e.canary.state_dict()
                cl = {"model": e.name, "canary": split["canary"]}
                samples.append(("mxtpu_serving_canary_fraction", cl,
                                split["fraction"]))
                samples.append(("mxtpu_serving_canary_stage", cl,
                                split["stage"]))
                samples.append((
                    "mxtpu_serving_canary_routed_total",
                    dict(cl, variant="canary"), split["routed_canary"]))
                samples.append((
                    "mxtpu_serving_canary_routed_total",
                    dict(cl, variant="incumbent"),
                    split["routed_incumbent"]))
            st = e.batcher.stats
            samples.append(("mxtpu_serving_breaker_state", labels,
                            self._BREAKER_STATE_ENUM.get(e.breaker.state,
                                                         -1)))
            samples.append(("mxtpu_serving_queue_depth", labels,
                            e.batcher.queue_depth))
            for key in ("requests_total", "rejected_total", "errors_total",
                        "shed_total", "degraded_total", "swaps_total",
                        "batches_total", "queue_depth_peak"):
                samples.append(("mxtpu_serving_" + key, labels,
                                getattr(st, key)))
            p50, p99 = st.latency_ms()
            samples.append(("mxtpu_serving_latency_p50_ms", labels, p50))
            samples.append(("mxtpu_serving_latency_p99_ms", labels, p99))
            for tier in ("gold", "silver", "bronze"):
                tp50, tp99 = st.tier_latency_ms(tier)
                tl = dict(labels, tier=tier)
                samples.append(("mxtpu_serving_tier_p50_ms", tl, tp50))
                samples.append(("mxtpu_serving_tier_p99_ms", tl, tp99))
            # decode entries: the per-token surface (PR-9 registry) —
            # token latency percentiles, token/step totals, page-pool
            # occupancy against the pages-based admission bound
            if hasattr(st, "token_latency_ms"):
                kp50, kp99 = st.token_latency_ms()
                samples.append(("mxtpu_decode_token_p50_ms", labels,
                                kp50))
                samples.append(("mxtpu_decode_token_p99_ms", labels,
                                kp99))
                samples.append(("mxtpu_decode_tokens_total", labels,
                                st.tokens_total))
                samples.append(("mxtpu_decode_steps_total", labels,
                                st.steps_total))
                samples.append(("mxtpu_decode_sequences_done_total",
                                labels, st.sequences_done_total))
                pool = getattr(e.runner, "pool", None)
                if pool is not None:
                    samples.append(("mxtpu_decode_pages_in_use", labels,
                                    pool.pages_in_use))
                    samples.append(("mxtpu_decode_pages_free", labels,
                                    pool.available))
        return samples

    # -- registration: admission control as a static problem ---------------
    def models(self):
        with self._lock:
            return list(self._entries)

    @property
    def default_model(self):
        with self._lock:
            return self._default

    def entry(self, name=None):
        with self._lock:
            key = name if name is not None else self._default
            try:
                return self._entries[key]
            except KeyError:
                raise UnknownModel(
                    "no model %r registered (have: %s)"
                    % (key, sorted(self._entries) or "none")) from None

    def runner(self, name=None):
        return self.entry(name).runner

    def batcher(self, name=None):
        return self.entry(name).batcher

    @staticmethod
    def _modeled_hbm(runner, hbm_bytes=None):
        # prefer the runner's own admission bound when it declares one:
        # fixed-shape runners price the max-over-buckets worst case,
        # decode runners price weights + KV page pool + one step's
        # working set — page-granular admission instead of assuming
        # every slot holds a full-context forward
        if hbm_bytes is not None:
            return int(hbm_bytes)
        admission = getattr(runner, "admission_hbm_bytes", None)
        if admission is not None:
            return admission()
        return runner.modeled_peak_hbm()

    def register(self, name, runner, fallback=None, hbm_bytes=None,
                 max_batch=None, batch_timeout_ms=None, max_queue=None,
                 service_time_hint_ms=None, breaker=None, tier_slos=None):
        """Host ``runner`` as ``name``.  Refused (``MXNetError`` carrying
        the SRV004 finding with the modeled per-model numbers) when the
        fleet's summed modeled peak HBM would exceed ``hbm_cap_bytes`` —
        over-commit is caught at registration, not at the first OOM.

        ``hbm_bytes`` overrides the modeled figure for runners the cost
        pass cannot see (Gluon blocks have no Symbol; their modeled HBM
        is None and only the modeled models count against the cap).
        ``fallback`` names the cheaper variant (registered before or
        after) that absorbs this model's overflow; ``tier_slos`` is the
        declared per-tier p99 budget (ms) surfaced in stats.
        """
        name = str(name)
        candidate = self._modeled_hbm(runner, hbm_bytes)
        with self._lock:
            if name in self._entries:
                raise MXNetError("model %r already registered; use swap()"
                                 % name)
            if self.hbm_cap_bytes:
                from ..analysis.serving_lint import lint_fleet_hbm
                packing = {e.name: e.hbm_bytes
                           for e in self._entries.values()}
                packing[name] = candidate
                findings = lint_fleet_hbm(packing, self.hbm_cap_bytes)
                if findings:
                    from ..analysis import render_text
                    raise MXNetError(
                        "fleet registration refused — modeled HBM over "
                        "cap:\n%s" % render_text(findings))
            breaker = breaker if breaker is not None else CircuitBreaker()
            batcher = Batcher(
                runner, max_batch=max_batch,
                batch_timeout_ms=self.batch_timeout_ms
                if batch_timeout_ms is None else batch_timeout_ms,
                max_queue=self.max_queue if max_queue is None
                else max_queue,
                stats=ServingStats(runner.buckets),
                service_time_hint_ms=service_time_hint_ms,
                on_batch_success=breaker.record_success,
                on_batch_error=lambda exc: breaker.record_failure(),
                model=name)
            entry = _Entry(name, batcher, breaker, candidate, fallback,
                           tier_slos)
            self._entries[name] = entry
            if self._default is None:
                self._default = name
        return entry

    def register_decode(self, name, runner, max_queue=None,
                        token_time_hint_ms=None, breaker=None,
                        tier_slos=None, hbm_bytes=None, eos_token=None):
        """Host a :class:`~mxnet_tpu.serving.decode.DecodeRunner` as
        ``name`` behind a continuous-batching
        :class:`~mxnet_tpu.serving.decode.DecodeBatcher`.

        Admission against the SRV004 cap uses the runner's pages-based
        ``admission_hbm_bytes()`` — weights + the KV page pool + one
        decode step's working set — so a decode model packs at page
        granularity next to fixed-shape models priced at their
        max-over-buckets worst case.  Requests route through
        :meth:`decode` / :meth:`decode_submit`; the fixed-shape
        :meth:`submit` path refuses decode entries.  Decode entries
        never hot-swap (live page tables index one runner's cache
        pool) — drain and re-register instead.
        """
        from .decode import DecodeBatcher, DecodeStats
        name = str(name)
        candidate = self._modeled_hbm(runner, hbm_bytes)
        with self._lock:
            if name in self._entries:
                raise MXNetError("model %r already registered; decode "
                                 "models drain and re-register" % name)
            if self.hbm_cap_bytes:
                from ..analysis.serving_lint import lint_fleet_hbm
                packing = {e.name: e.hbm_bytes
                           for e in self._entries.values()}
                packing[name] = candidate
                findings = lint_fleet_hbm(packing, self.hbm_cap_bytes)
                if findings:
                    from ..analysis import render_text
                    raise MXNetError(
                        "fleet registration refused — modeled HBM over "
                        "cap:\n%s" % render_text(findings))
            breaker = breaker if breaker is not None else CircuitBreaker()
            batcher = DecodeBatcher(
                runner,
                max_queue=self.max_queue if max_queue is None
                else max_queue,
                token_time_hint_ms=token_time_hint_ms,
                stats=DecodeStats(runner.buckets),
                on_step_success=breaker.record_success,
                on_step_error=lambda exc: breaker.record_failure(),
                model=name, eos_token=eos_token)
            entry = _Entry(name, batcher, breaker, candidate, None,
                           tier_slos)
            self._entries[name] = entry
            if self._default is None:
                self._default = name
        return entry

    @staticmethod
    def _is_decode(entry):
        return hasattr(entry.batcher, "schedule_events")

    def decode_submit(self, prompt, model=None, max_new_tokens=16,
                      tier=DEFAULT_TIER, deadline_ms=None, on_token=None):
        """Route one prompt to a decode model; returns a future-like
        whose ``result()`` is the generated token array.  Same refusal
        surface as :meth:`submit` (:class:`BreakerOpen` /
        :class:`RequestShed` / :class:`ServerBusy` / :class:`Draining`);
        no fallback rerouting — decode models declare none."""
        entry = self.entry(model)
        if not self._is_decode(entry):
            raise MXNetError(
                "model %r is a fixed-shape model; use fleet.submit()"
                % entry.name)
        if not entry.breaker.allow():
            raise BreakerOpen(
                "model %r breaker is open; failing fast" % entry.name,
                model=entry.name,
                retry_after_s=entry.breaker.retry_after_s())
        return entry.batcher.submit(
            prompt, max_new_tokens=max_new_tokens, tier=tier,
            deadline_ms=deadline_ms, on_token=on_token)

    def decode(self, prompt, model=None, max_new_tokens=16, timeout=60.0,
               tier=DEFAULT_TIER, deadline_ms=None, on_token=None):
        """Blocking decode: submit + wait for the generated tokens."""
        fut = self.decode_submit(prompt, model=model,
                                 max_new_tokens=max_new_tokens,
                                 tier=tier, deadline_ms=deadline_ms,
                                 on_token=on_token)
        return fut.result(timeout)

    def provenance_digests(self):
        """{model: checkpoint digest or None} — the hello-path summary
        of what bytes are live (full provenance rides ``stats_dict``)."""
        with self._lock:
            entries = list(self._entries.values())
        out = {}
        for e in entries:
            prov = getattr(e.runner, "provenance", None)
            out[e.name] = prov.get("digest") if prov else None
        return out

    def modeled_hbm_total(self):
        """Summed modeled peak HBM over registered models (None-modeled
        runners excluded) — the packing ledger /stats exposes."""
        with self._lock:
            return sum(e.hbm_bytes for e in self._entries.values()
                       if e.hbm_bytes)

    # -- canary traffic split ----------------------------------------------
    def set_canary(self, model, canary, schedule=DEFAULT_CANARY_SCHEDULE,
                   seed=0):
        """Arm a deterministic traffic split: ``canary`` (an already-
        registered model, typically the promotion candidate) receives
        the seeded hash slice of ``model``'s requests at the schedule's
        current fraction.  The split is advanced explicitly
        (:meth:`advance_canary` — the promotion controller's decision),
        never by wall clock.  Returns the :class:`CanarySplit`.

        The canary runner must share the incumbent's ``example_shape``
        (the same request bytes must be valid on either variant)."""
        entry = self.entry(model)
        c_entry = self.entry(canary)
        if c_entry is entry:
            raise MXNetError("a model cannot canary itself (%r)" % model)
        if tuple(c_entry.runner.example_shape) != \
                tuple(entry.runner.example_shape):
            raise MXNetError(
                "canary refused: example_shape %r != incumbent's %r — "
                "split traffic would feed one variant bad geometry"
                % (tuple(c_entry.runner.example_shape),
                   tuple(entry.runner.example_shape)))
        split = CanarySplit(c_entry.name, schedule=schedule, seed=seed)
        with self._lock:
            if entry.canary_of:
                raise MXNetError(
                    "model %r is itself the canary of %r — clear that "
                    "split first" % (entry.name, entry.canary_of))
            entry.canary = split
            c_entry.canary_of = entry.name
        return split

    def clear_canary(self, model):
        """Disarm ``model``'s traffic split (rollback or post-promotion
        cleanup); returns the removed :class:`CanarySplit` or None."""
        entry = self.entry(model)
        with self._lock:
            split, entry.canary = entry.canary, None
            if split is not None:
                c = self._entries.get(split.canary)
                if c is not None and c.canary_of == entry.name:
                    c.canary_of = None
        return split

    def advance_canary(self, model):
        """Step ``model``'s canary ramp to the next pinned fraction;
        returns the new fraction."""
        split = self.entry(model).canary
        if split is None:
            raise MXNetError("model %r has no canary armed" % (model,))
        return split.advance()

    def canary_state(self, model):
        """The split's state dict (fraction/stage/routed counts), or
        None when no split is armed."""
        split = self.entry(model).canary
        return None if split is None else split.state_dict()

    # -- routing -----------------------------------------------------------
    def submit(self, example, model=None, tier=DEFAULT_TIER,
               deadline_ms=None, request_id=None):
        """Route one example: returns a future-like with ``.result()``.

        Overload ladder: an open breaker or a shed/full-queue refusal on
        the primary reroutes to its registered ``fallback`` (degraded
        mode) when that variant is warm and closed; only when the
        fallback also refuses does the caller see the original
        :class:`RequestShed` / :class:`BreakerOpen` / :class:`ServerBusy`.

        With a canary split armed on the routed model, ``request_id``
        seeds the deterministic hash split (falls back to the fleet's
        route ordinal when absent — still deterministic within a seeded
        run).  A canary-routed request the canary refuses falls back to
        the incumbent, billed to the *canary's* degraded counter — the
        incumbent's ledger never pays for canary trouble.
        """
        from ..resilience import chaos as _chaos
        entry = self.entry(model)
        if self._is_decode(entry):
            raise MXNetError(
                "model %r serves autoregressive decode; use "
                "fleet.decode()/decode_submit()" % entry.name)
        with self._lock:
            self._route_seq += 1
            seq = self._route_seq
        _chaos.maybe_inject("serving.route", count=seq,
                            ctx=(entry.name, tier))
        self._check_shape(entry, example)
        split = entry.canary
        if split is not None:
            rid = request_id if request_id is not None else seq
            to_canary = split.routes_to_canary(rid)
            split.record_route(to_canary)
            if to_canary:
                c_entry = self.entry(split.canary)
                try:
                    # no registered-fallback hop for the canary slice:
                    # its safety net is the incumbent itself, below
                    return self._submit_entry(c_entry, example, tier,
                                              deadline_ms,
                                              allow_fallback=False)
                except (RequestShed, ServerBusy, BreakerOpen):
                    # canary refused -> the incumbent absorbs; the
                    # degrade bills the CANARY (per-variant attribution)
                    c_entry.batcher.stats.on_degraded()
                    return self._submit_entry(entry, example, tier,
                                              deadline_ms,
                                              allow_fallback=True)
        return self._submit_entry(entry, example, tier, deadline_ms,
                                  allow_fallback=True)

    def _check_shape(self, entry, example):
        import numpy as _np
        shape = _np.asarray(example).shape
        want = tuple(entry.runner.example_shape)
        if tuple(shape) != want:
            raise MXNetError(
                "example shape %r does not match model %r example_shape "
                "%r" % (tuple(shape), entry.name, want))

    def _fallback_entry(self, entry):
        if not entry.fallback:
            return None
        with self._lock:
            fb = self._entries.get(entry.fallback)
        if fb is None or not getattr(fb.runner, "warmed_up", False):
            return None
        if not fb.breaker.allow() or fb.batcher.draining:
            return None
        return fb

    def _submit_entry(self, entry, example, tier, deadline_ms,
                      allow_fallback):
        if not entry.breaker.allow():
            fb = self._fallback_entry(entry) if allow_fallback else None
            if fb is not None:
                entry.batcher.stats.on_degraded()
                return self._submit_entry(fb, example, tier, deadline_ms,
                                          allow_fallback=False)
            raise BreakerOpen(
                "model %r breaker is open (%d consecutive batch "
                "failures tripped it); retry after %.1fs"
                % (entry.name, entry.breaker.failure_threshold,
                   entry.breaker.retry_after_s()),
                model=entry.name,
                retry_after_s=max(1.0, math.ceil(
                    entry.breaker.retry_after_s())))
        try:
            return entry.batcher.submit(example, tier=tier,
                                        deadline_ms=deadline_ms,
                                        model=entry.name)
        except (RequestShed, ServerBusy):
            fb = self._fallback_entry(entry) if allow_fallback else None
            if fb is None:
                raise
            entry.batcher.stats.on_degraded()
            return self._submit_entry(fb, example, tier, deadline_ms,
                                      allow_fallback=False)

    def infer(self, example, model=None, tier=DEFAULT_TIER,
              deadline_ms=None, timeout=30.0, request_id=None):
        """Blocking convenience: route + wait for the result row."""
        return self.submit(example, model=model, tier=tier,
                           deadline_ms=deadline_ms,
                           request_id=request_id).result(timeout)

    # -- hot swap ----------------------------------------------------------
    def swap(self, name, runner, warmup=True, timeout=30.0):
        """Replace model ``name``'s runner under drain of its in-flight
        batch: the new runner is warmed first (nothing is routed to a
        cold bucket ladder), the swap waits for the executing batch, and
        queued requests are served by the replacement — zero failed
        in-flight requests.  The breaker resets (a fresh runner deserves
        a fresh failure budget).  Returns the previous runner; the blip
        (ms the swap waited on the in-flight batch) lands in
        ``stats_dict()``."""
        from ..resilience import chaos as _chaos
        entry = self.entry(name)
        _chaos.maybe_inject("serving.swap", ctx=entry.name)
        if warmup and not getattr(runner, "warmed_up", False):
            runner.warmup()
        t0 = time.monotonic()
        old = entry.batcher.swap_runner(runner, timeout=timeout)
        entry.last_swap_blip_ms = (time.monotonic() - t0) * 1000.0
        entry.breaker.reset()
        return old

    def deregister(self, name, timeout=30.0):
        """Remove model ``name`` from the fleet after draining its
        batcher (queued requests complete; new ones 404).  Refused while
        the model is the default, someone's fallback, or half of an
        armed canary split — routing must never dangle.  Returns the
        removed runner (the promotion controller's rollback path)."""
        entry = self.entry(name)
        with self._lock:
            if self._default == entry.name and len(self._entries) > 1:
                raise MXNetError(
                    "cannot deregister the default model %r" % name)
            if entry.canary is not None or entry.canary_of:
                raise MXNetError(
                    "model %r is part of an armed canary split; "
                    "clear_canary() first" % name)
            dependents = [e.name for e in self._entries.values()
                          if e.fallback == entry.name]
            if dependents:
                raise MXNetError(
                    "model %r is the registered fallback of %s — "
                    "re-point them first" % (name, dependents))
        entry.batcher.drain(timeout=timeout)
        with self._lock:
            self._entries.pop(entry.name, None)
            if self._default == entry.name:
                self._default = next(iter(self._entries), None)
        return entry.runner

    # -- readiness ---------------------------------------------------------
    def unready(self):
        """{model: reason} for every model not currently routable:
        ``warming`` (bucket ladder not compiled), ``breaker_open`` /
        ``breaker_half_open`` (tripped on repeated failures), ``stalled``
        (in-flight batch exceeded ``stall_threshold_s``), ``draining``.
        Empty dict == the fleet is ready (the /readyz contract)."""
        out = {}
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if not getattr(e.runner, "warmed_up", False):
                out[e.name] = "warming"
            elif e.breaker.state != "closed":
                out[e.name] = "breaker_%s" % e.breaker.state
            elif e.batcher.stalled(self.stall_threshold_s):
                out[e.name] = "stalled"
            elif e.batcher.draining:
                out[e.name] = "draining"
        return out

    @property
    def ready(self):
        return not self.unready()

    @property
    def draining(self):
        with self._lock:
            entries = list(self._entries.values())
        return any(e.batcher.draining for e in entries)

    # -- observability -----------------------------------------------------
    def stats_dict(self):
        """Per-model stats + the fleet packing/routing ledger."""
        with self._lock:
            entries = list(self._entries.values())
            cap = self.hbm_cap_bytes
            default = self._default
        models = {}
        for e in entries:
            d = e.batcher.stats.as_dict()
            d["breaker"] = e.breaker.state
            d["fallback"] = e.fallback
            d["tier_slos_ms"] = dict(e.tier_slos)
            d["modeled_peak_hbm_bytes"] = e.hbm_bytes
            d["queue_depth"] = e.batcher.queue_depth
            d["modeled_wait_ms"] = round(e.batcher.modeled_wait_ms(), 3)
            d["recompiles"] = e.runner.recompiles_since_warmup()
            d["buckets_configured"] = list(e.runner.buckets)
            if self._is_decode(e):
                d["page_pool"] = e.runner.pool.describe()
            # checkpoint provenance: which exact bytes this entry serves
            # (digest + epoch/step/train_run_id, or None for untracked
            # runners) — what promotion audit records cross-reference
            d["provenance"] = getattr(e.runner, "provenance", None)
            if e.canary is not None:
                d["canary"] = e.canary.state_dict()
            if e.canary_of:
                d["canary_of"] = e.canary_of
            if e.last_swap_blip_ms is not None:
                d["last_swap_blip_ms"] = round(e.last_swap_blip_ms, 3)
            models[e.name] = d
        return {
            "models": models,
            "default_model": default,
            "hbm_cap_bytes": cap,
            "modeled_hbm_total_bytes": self.modeled_hbm_total(),
            "unready": self.unready(),
        }

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout=60.0):
        """Drain every model's batcher against one shared deadline.
        Raises ``TimeoutError`` (after attempting all) when any batcher
        missed it — callers holding a hard deadline follow up with
        :meth:`force_drain`."""
        deadline = time.monotonic() + float(timeout)
        late = []
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            try:
                e.batcher.drain(timeout=max(0.05,
                                            deadline - time.monotonic()))
            except TimeoutError:
                late.append(e.name)
        if late:
            raise TimeoutError("fleet did not drain within %ss "
                               "(stuck: %s)" % (timeout, late))
        return True

    def force_drain(self):
        with self._lock:
            entries = list(self._entries.values())
        return sum(e.batcher.force_drain() for e in entries)

    def __repr__(self):
        with self._lock:
            names, default = list(self._entries), self._default
        return "<ModelFleet %s default=%r>" % (names, default)
