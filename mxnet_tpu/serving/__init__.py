"""mxnet_tpu.serving — multi-model, SLO-tiered, overload-proof inference.

The training side of this framework reached parity rounds ago; this
package is the deployment half the reference papers treat as first-class
(TensorFlow ships serving beside training, and MXNet motivates its
symbolic executor with packaged inference).  Four layers:

- :class:`~mxnet_tpu.serving.runner.ModelRunner` — a bound Module or
  hybridized Gluon block behind a fixed ladder of padded batch buckets
  (default 1/4/16/64), all compiled ahead of time at load, with the
  jit-cache key set exposed so steady-state traffic provably never
  recompiles;
- :class:`~mxnet_tpu.serving.batcher.Batcher` — a thread that coalesces
  concurrent requests deadline-aware up to ``max_batch``/
  ``batch_timeout_ms``, pads to the nearest bucket, splits results per
  request, and — before its bounded queue can collapse — sheds
  deterministically, lowest SLO tier first, every request whose modeled
  queue wait exceeds its ``deadline_ms``;
- :class:`~mxnet_tpu.serving.fleet.ModelFleet` — N named runners behind
  one routing surface: HBM-aware packing at registration (modeled cost
  vs the SRV004 cap), per-model circuit breakers
  (:class:`~mxnet_tpu.serving.fleet.CircuitBreaker`), degraded-mode
  rerouting to a registered cheaper variant (the int8 path), hot
  model swap under drain with zero failed in-flight requests, and a
  deterministic canary traffic split
  (:class:`~mxnet_tpu.serving.fleet.CanarySplit` — seeded request-id
  hash, pinned fraction ramp, per-variant attribution; the routing
  substrate ``mxnet_tpu.mlops`` promotes over);
- :class:`~mxnet_tpu.serving.server.Server` — a stdlib-HTTP front end
  with ``/predict`` (model/tier/deadline routing), per-model
  ``/readyz`` vs process ``/livez``, ``/healthz``, ``/stats``, bounded
  request bodies (413) and graceful drain;
- :mod:`~mxnet_tpu.serving.decode` — the autoregressive tier: a paged
  KV-cache allocator (:class:`~mxnet_tpu.serving.decode.PagePool`), the
  prefill/decode split behind the same recompile-free contract
  (:class:`~mxnet_tpu.serving.decode.DecodeRunner`), and continuous
  batching with the SLO arithmetic generalized to tokens-remaining
  (:class:`~mxnet_tpu.serving.decode.DecodeBatcher`) — the fleet serves
  the transformer the repo trains (``ModelFleet.register_decode`` /
  ``.decode``).

See ``docs/serving.md``, ``tools/serve.py`` (CLI) and
``examples/serving/`` (end-to-end demo).
"""
from __future__ import annotations

from .runner import ModelRunner, DEFAULT_BUCKETS
from .batcher import (Batcher, ServerBusy, Draining, RequestShed,
                      TIERS, DEFAULT_TIER, tier_rank, tier_name)
from .fleet import (ModelFleet, CircuitBreaker, BreakerOpen, UnknownModel,
                    CanarySplit, DEFAULT_CANARY_SCHEDULE)
from .server import Server
from .stats import ServingStats, percentile
from .decode import (PagePool, NoPagesFree, DecodeRunner, DecodeBatcher,
                     DecodeStats)

__all__ = ["ModelRunner", "DEFAULT_BUCKETS", "Batcher", "ServerBusy",
           "Draining", "RequestShed", "TIERS", "DEFAULT_TIER",
           "tier_rank", "tier_name", "ModelFleet", "CircuitBreaker",
           "BreakerOpen", "UnknownModel", "CanarySplit",
           "DEFAULT_CANARY_SCHEDULE", "Server", "ServingStats",
           "percentile", "PagePool", "NoPagesFree", "DecodeRunner",
           "DecodeBatcher", "DecodeStats"]
