"""mxnet_tpu.serving — dynamic-batching inference with bucketed,
recompile-free execution.

The training side of this framework reached parity rounds ago; this
package is the deployment half the reference papers treat as first-class
(TensorFlow ships serving beside training, and MXNet motivates its
symbolic executor with packaged inference).  Three layers:

- :class:`~mxnet_tpu.serving.runner.ModelRunner` — a bound Module or
  hybridized Gluon block behind a fixed ladder of padded batch buckets
  (default 1/4/16/64), all compiled ahead of time at load, with the
  jit-cache key set exposed so steady-state traffic provably never
  recompiles;
- :class:`~mxnet_tpu.serving.batcher.Batcher` — a thread that coalesces
  concurrent requests up to ``max_batch``/``batch_timeout_ms``, pads to
  the nearest bucket, splits results per request, and rejects (never
  stalls) when its bounded queue fills;
- :class:`~mxnet_tpu.serving.server.Server` — a stdlib-HTTP front end
  with ``/predict``, ``/healthz`` and ``/stats`` plus graceful drain.

See ``docs/serving.md``, ``tools/serve.py`` (CLI) and
``examples/serving/`` (end-to-end demo).
"""
from __future__ import annotations

from .runner import ModelRunner, DEFAULT_BUCKETS
from .batcher import Batcher, ServerBusy, Draining
from .server import Server
from .stats import ServingStats, percentile

__all__ = ["ModelRunner", "DEFAULT_BUCKETS", "Batcher", "ServerBusy",
           "Draining", "Server", "ServingStats", "percentile"]
