"""Host-runnable decode micro-benchmark.

Measures the autoregressive serving tier's headline numbers through the
full DecodeRunner→DecodeBatcher path — ``decode_tokens_per_sec_host``
(continuous-batching throughput under a seeded mixed-length concurrent
burst), ``decode_p50/p99_per_token_ms`` (per generated token, the SLO
unit the tokens-remaining shed arithmetic prices in) — plus the two
hard contracts as 0/1 keys the compare gate holds at zero slack:
``decode_numerics_ok`` (a paged-cache greedy decode must match the
no-cache full-forward reference EXACTLY) and ``decode_recompiles``
(zero steady-state jit-cache growth after the AOT warmup ladder, the
``ModelRunner`` contract extended to the prefill-bucket × decode-slot
surface).  Deliberately TPU-independent (the r5 failure mode: every
key starved behind backend acquisition); ``bench.py`` runs this module
as a ``JAX_PLATFORMS=cpu`` subprocess, and it can be run directly:

    JAX_PLATFORMS=cpu python -m mxnet_tpu.serving.decode_bench
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as _np

__all__ = ["decode_bench"]


def _build_runner(slots=4):
    from ..parallel.mesh import MeshPlan
    from ..transformer import TransformerLMConfig
    from ..transformer.decode import DecodeProgram
    from .decode import DecodeRunner

    cfg = TransformerLMConfig(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64, seq_len=64)
    prog = DecodeProgram(cfg, plan=MeshPlan(data=1), page_size=8)
    params = prog.program.init_params(0)
    return DecodeRunner(prog, params, slots=slots,
                        prefill_buckets=(8, 16, 32), warmup=True)


def decode_bench(n_requests=None, concurrency=None, slots=4):
    """Fire ``n_requests`` mixed-length, mixed-tier prompts from
    ``concurrency`` client threads through a DecodeBatcher; returns the
    stable bench keys."""
    from .batcher import RequestShed, ServerBusy
    from .decode import DecodeBatcher
    from .stats import percentile

    n_requests = n_requests or int(os.environ.get(
        "MXTPU_DECODE_BENCH_N", "48"))
    concurrency = concurrency or int(os.environ.get(
        "MXTPU_SERVING_BENCH_CONCURRENCY", "8"))
    runner = _build_runner(slots=slots)

    # the numerics contract BEFORE the batcher exists (the page pool has
    # one owner): cached greedy decode == no-cache full-forward reference
    rng = _np.random.RandomState(0)
    numerics_ok = 1
    for trial in range(3):
        prompt = rng.randint(1, 64, size=rng.randint(3, 12)
                             ).astype(_np.int32)
        cached = runner.generate(prompt, 8)
        ref = runner.reference_decode(prompt, 8)
        if not _np.array_equal(cached, ref):
            numerics_ok = 0
            break

    batcher = DecodeBatcher(runner, max_queue=max(64, n_requests),
                            model="bench")
    lengths = [3, 5, 8, 11, 16, 24]       # mixed prefill buckets
    tiers = ["gold", "silver", "bronze"]
    tokens_done = []
    lock = threading.Lock()
    shed = [0]
    per_thread = n_requests // concurrency

    def client(tid):
        got, drop = 0, 0
        r = _np.random.RandomState(100 + tid)
        for i in range(per_thread):
            n = lengths[(tid + i) % len(lengths)]
            prompt = r.randint(1, 64, size=n).astype(_np.int32)
            try:
                out = batcher.decode(prompt, max_new_tokens=8,
                                     tier=tiers[(tid + i) % len(tiers)],
                                     timeout=120)
                got += len(out)
            except (RequestShed, ServerBusy):
                drop += 1
        with lock:
            tokens_done.append(got)
            shed[0] += drop

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    batcher.drain()

    st = batcher.stats
    p50, p99 = st.token_latency_ms()
    total_tokens = sum(tokens_done)
    return {
        "decode_tokens_per_sec_host": round(total_tokens / wall, 2)
        if wall else 0.0,
        "decode_p50_per_token_ms": round(p50, 3),
        "decode_p99_per_token_ms": round(p99, 3),
        "decode_numerics_ok": numerics_ok,
        "decode_recompiles": runner.recompiles_since_warmup(),
        "decode_tokens_total": total_tokens,
        "decode_requests_shed": shed[0],
        "decode_pages_leaked": runner.pool.pages_in_use,
        "decode_concurrency": concurrency,
    }


def main():
    out = decode_bench()
    print(json.dumps(out), flush=True)
    # the contract bench.py's stage relies on: exact numerics through
    # the paged cache, zero steady-state recompiles, zero leaked pages
    return 0 if (out["decode_numerics_ok"] == 1
                 and out["decode_recompiles"] == 0
                 and out["decode_pages_leaked"] == 0) else 1


if __name__ == "__main__":
    sys.exit(main())
