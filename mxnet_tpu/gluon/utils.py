"""Gluon utilities (reference: ``python/mxnet/gluon/utils.py``)."""
from __future__ import annotations

import hashlib
import math
import os

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks (reference: utils.py:31)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "batch size %d cannot be evenly split into %d slices"
            % (size, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data and load each slice onto a context (reference: utils.py:81).
    On a TPU mesh the physical split happens via sharding; this keeps API
    parity for multi-context scripts."""
    if not isinstance(data, NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so total L2 norm <= max_norm (reference: utils.py:118)."""
    total = 0.0
    for arr in arrays:
        n = float(arr.norm().asscalar())
        total += n * n
    total = math.sqrt(total)
    if not np.isfinite(total):
        import warnings
        warnings.warn("nan or inf in gradient norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def get_repo_url():
    """Hosted-artifact repo base URL, MXNET_GLUON_REPO-overridable with a
    guaranteed trailing slash (shared by model_store and contrib.text;
    reference: gluon/utils.py:243 _get_repo_url)."""
    repo = os.environ.get(
        "MXNET_GLUON_REPO",
        "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/")
    if not repo.endswith("/"):
        repo += "/"
    return repo


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True, timeout=30):
    """Download ``url`` to ``path`` with SHA-1 verification and retries
    (reference: gluon/utils.py:178 download).

    ``file://`` URLs ride the same urllib code path, so the full
    download+verify+retry logic is unit-testable in this zero-egress
    environment; http(s) URLs raise after exhausting retries."""
    import shutil
    import tempfile
    import urllib.request

    if path is None:
        fname = url.split("/")[-1]
        assert fname, "can't construct file-name from %r" % url
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    assert retries >= 0, "number of retries should be at least 0"

    if overwrite or not os.path.exists(fname) or \
            (sha1_hash and not check_sha1(fname, sha1_hash)):
        dirname = os.path.dirname(os.path.abspath(os.path.expanduser(fname)))
        if not os.path.exists(dirname):
            os.makedirs(dirname)
        last_err = None
        while retries + 1 > 0:
            try:
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    # write to a temp file then move: a killed transfer
                    # must never leave a truncated file at fname that a
                    # later call would trust
                    fd, tmp = tempfile.mkstemp(dir=dirname)
                    try:
                        with os.fdopen(fd, "wb") as out:
                            shutil.copyfileobj(resp, out)
                        shutil.move(tmp, fname)
                    finally:
                        if os.path.exists(tmp):
                            os.remove(tmp)
                if sha1_hash and not check_sha1(fname, sha1_hash):
                    raise IOError(
                        "downloaded file %r sha1 mismatch: expected %s. "
                        "The repo may be out of sync with the catalog; "
                        "try overwrite=True or update the hash."
                        % (fname, sha1_hash))
                return fname
            except Exception as e:
                last_err = e
                retries -= 1
                if retries < 0:
                    raise IOError(
                        "failed to download %r: %s (no network egress in "
                        "this environment for http(s); file:// works)"
                        % (url, e)) from last_err
    return fname
