"""Gluon utilities (reference: ``python/mxnet/gluon/utils.py``)."""
from __future__ import annotations

import hashlib
import math
import os

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks (reference: utils.py:31)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "batch size %d cannot be evenly split into %d slices"
            % (size, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data and load each slice onto a context (reference: utils.py:81).
    On a TPU mesh the physical split happens via sharding; this keeps API
    parity for multi-context scripts."""
    if not isinstance(data, NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so total L2 norm <= max_norm (reference: utils.py:118)."""
    total = 0.0
    for arr in arrays:
        n = float(arr.norm().asscalar())
        total += n * n
    total = math.sqrt(total)
    if not np.isfinite(total):
        import warnings
        warnings.warn("nan or inf in gradient norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Download helper (reference: utils.py download).  This environment has
    no egress; only file:// and existing local paths are honored."""
    fname = url.split("/")[-1] if path is None else path
    if os.path.isdir(fname):
        fname = os.path.join(fname, url.split("/")[-1])
    if os.path.exists(fname) and not overwrite:
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[7:], fname)
        return fname
    raise IOError(
        "cannot download %r: no network egress in this environment; place the "
        "file at %r manually" % (url, fname))
