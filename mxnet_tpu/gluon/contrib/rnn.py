"""gluon.contrib.rnn (reference: python/mxnet/gluon/contrib/rnn/ —
VariationalDropoutCell, Conv RNN cells).  VariationalDropoutCell applies
the same dropout mask at every timestep (Gal & Ghahramani)."""
from __future__ import annotations

from ... import ndarray as nd
from ..rnn.rnn_cell import ModifierCell, BidirectionalCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        assert not drop_states or not isinstance(base_cell,
                                                 BidirectionalCell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        super().__init__(base_cell)
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def _mask_like(self, p, like):
        # sampled once per unroll, reused each step (variational dropout)
        return nd.Dropout(nd.ones_like(like), p=p, mode="always")

    def hybrid_forward(self, F, inputs, states):
        from ... import autograd
        if not autograd.is_training():
            # identity at inference (reference: masks only under train mode)
            return self.base_cell(inputs, states)
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask_like(self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_masks is None:
                self._state_masks = [
                    self._mask_like(self.drop_states, s) for s in states]
            states = [s * m for s, m in zip(states, self._state_masks)]
        out, new_states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask_like(self.drop_outputs, out)
            out = out * self._output_mask
        return out, new_states
