"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py — Concurrent, HybridConcurrent, Identity,
SparseEmbedding)."""
from __future__ import annotations

from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..nn.basic_layers import HybridConcurrent, Sequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs
    (reference: contrib Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding backed by a row_sparse weight — only the rows a batch
    touches are updated (reference: contrib SparseEmbedding; pairs with
    kvstore row_sparse_pull for distributed training)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse")

    def forward(self, x):
        return nd.Embedding(x, self.weight.data(),
                            input_dim=self._input_dim,
                            output_dim=self._output_dim,
                            sparse_grad=True)

    def __repr__(self):
        return "SparseEmbedding(%d -> %d)" % (self._input_dim,
                                              self._output_dim)
