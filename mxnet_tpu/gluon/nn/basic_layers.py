"""Gluon basic NN layers (reference: ``python/mxnet/gluon/nn/basic_layers.py``)."""
from __future__ import annotations

import numpy as np

from ... import ndarray as nd
from ..block import Block, HybridBlock


class Sequential(Block):
    """Stack of blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self._flatten = flatten
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer, dtype=dtype,
                    allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None
            if self.act is not None:
                self.register_child(self.act, "act")

    def infer_param_shapes(self, x, *args):
        if self.weight._deferred_init:
            in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type or "activation"

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class BatchNorm(HybridBlock):
    """Reference: basic_layers.py BatchNorm (axis=1, NCHW default)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p._deferred_init:
                p.shape = (c,)

    def cast(self, dtype):
        # BN statistics stay fp32 under half-precision training, matching the
        # reference's BatchNorm.cast fp16 behavior (gluon/nn/basic_layers.py);
        # bf16 gets the same treatment on TPU.
        import numpy as _np
        import jax.numpy as _jnp
        if _np.dtype(dtype) in (_np.dtype(_np.float16), _np.dtype(_jnp.bfloat16)):
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._deferred_init:
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._deferred_init:
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), init=weight_initializer,
                dtype=dtype, grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_impl = getattr(nd, function)
            self._func_name = function
        else:
            self._func_impl = function
            self._func_name = function.__name__
    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func = lambda F, *args: getattr(F, function)(*args)
        else:
            self._func = lambda F, *args: function(F, *args)
            self._func_name = function.__name__

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)


class HybridConcurrent(HybridBlock):
    """Run children on the same input and concat outputs on ``axis``
    (reference: gluon/contrib/nn/basic_layers.py HybridConcurrent)."""

    def __init__(self, axis=1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, block):
        self.register_child(block)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)
