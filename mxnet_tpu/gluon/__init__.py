"""Gluon: the imperative high-level API (reference: python/mxnet/gluon/)."""
from . import nn
from . import rnn
from . import data
from . import contrib
from . import loss
from . import utils
from . import model_zoo
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Parameter, ParameterDict, Constant
from .trainer import Trainer
