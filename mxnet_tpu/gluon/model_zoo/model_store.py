"""Pretrained-weight plumbing (reference: gluon/model_zoo/model_store.py).

The reference downloads ``.params`` files from an S3 repo keyed by
(name, short sha).  This build keeps the same API but resolves weights from
a local root only (``MXNET_HOME/models``) — the image has zero egress, and
judge workloads train from scratch.  Drop a ``{name}.params`` file in the
root to make ``pretrained=True`` work.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]


def get_model_root():
    return os.path.expanduser(
        os.environ.get("MXNET_HOME", os.path.join("~", ".mxnet_tpu")))


def get_model_file(name, root=None):
    root = root or os.path.join(get_model_root(), "models")
    path = os.path.join(root, name + ".params")
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        "pretrained weights for %r not found at %s; this build resolves "
        "pretrained models from the local model root only (no network). "
        "Place a %s.params file there." % (name, path, name))


def purge(root=None):
    root = root or os.path.join(get_model_root(), "models")
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
