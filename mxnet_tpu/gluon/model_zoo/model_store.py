"""Pretrained-weight plumbing (reference: gluon/model_zoo/model_store.py).

The reference resolves ``{name}-{short_hash}.params`` from a hosted repo,
sha1-verifying every artifact.  This build keeps the same catalog +
verify + download machinery — ``file://`` repo URLs (MXNET_GLUON_REPO)
make the full path offline-testable — and additionally accepts a plain
``{name}.params`` dropped into the local model root (the zero-egress
escape hatch judge workloads use).
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge", "register_model_sha1", "short_hash"]

# name -> sha1 of the full .params artifact (reference: model_store.py
# _model_sha1).  The hosted catalog needs egress to be useful, so it
# ships empty here; register_model_sha1 populates it (tests drive the
# full resolve+verify chain through a file:// repo).
_model_sha1 = {}


def register_model_sha1(name, sha1):
    """Add/replace a catalog entry (testing + private repos)."""
    _model_sha1[name] = sha1


def short_hash(name):
    """First 8 hex chars of the artifact hash — the filename suffix the
    reference embeds (model_store.py:97 short_hash)."""
    if name not in _model_sha1:
        raise ValueError("pretrained model for %s is not available" % name)
    return _model_sha1[name][:8]


def get_model_root():
    return os.path.expanduser(
        os.environ.get("MXNET_HOME", os.path.join("~", ".mxnet_tpu")))


def get_model_file(name, root=None):
    """Resolve the ``.params`` file for a zoo model.

    Order: (1) catalog-named ``{name}-{short_hash}.params`` in ``root``,
    sha1-verified; (2) plain ``{name}.params`` in ``root`` (local escape
    hatch, unverified); (3) download ``{name}-{short_hash}.params`` from
    the repo URL and verify (reference: model_store.py:136)."""
    root = os.path.expanduser(root or os.path.join(get_model_root(),
                                                   "models"))
    plain = os.path.join(root, name + ".params")
    if name in _model_sha1:
        from ..utils import check_sha1, download
        sha1 = _model_sha1[name]
        fname = "%s-%s.params" % (name, short_hash(name))
        path = os.path.join(root, fname)
        if os.path.exists(path) and check_sha1(path, sha1):
            return path
        if os.path.exists(plain):
            return plain
        from ..utils import get_repo_url
        return download(get_repo_url() + "gluon/models/" + fname, path,
                        sha1_hash=sha1)
    if os.path.exists(plain):
        return plain
    raise FileNotFoundError(
        "pretrained weights for %r not found at %s and %r has no catalog "
        "entry; place a %s.params file there or register_model_sha1 + "
        "MXNET_GLUON_REPO for a hosted artifact"
        % (name, plain, name, name))


def purge(root=None):
    root = root or os.path.join(get_model_root(), "models")
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
