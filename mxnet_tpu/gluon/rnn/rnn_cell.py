"""Recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py).

Cells compute one timestep; ``unroll`` runs them over a sequence.  On TPU an
unrolled cell under ``hybridize()`` compiles to a single XLA program — for
long sequences prefer the fused layers (rnn_layer.py) whose ``lax.scan``
compiles in O(1) graph size.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import Block, HybridBlock


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


class RecurrentCell(Block):
    """Base class for recurrent cells."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called directly"
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            info.pop("__layout__", None)
            states.append(func(**info, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over `length` timesteps
        (reference: rnn_cell.py unroll)."""
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch_size = seq[0].shape[batch_axis if batch_axis < axis
                                      else batch_axis - 1]
        else:
            batch_size = inputs.shape[batch_axis]
            seq = [nd.squeeze(s, axis=axis) for s in
                   nd.split(inputs, num_outputs=length, axis=axis)]
            if length == 1:
                seq = [nd.squeeze(inputs, axis=axis)]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            out, states = self(seq[i], states)
            outputs.append(out)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            # final states taken at t = valid_length-1, not at the padded end
            # (reference: rnn_cell.py unroll SequenceLast over stacked states)
            states = [nd.SequenceLast(nd.stack(*ele, axis=0), valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele in zip(*all_states)]
            outputs = [nd.where(
                nd.broadcast_lesser(nd.full((1,), i), valid_length.reshape(-1, 1)),
                o, nd.zeros_like(o)) for i, o in enumerate(outputs)]
        if merge_outputs is None or merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _BaseGatedCell(HybridRecurrentCell):
    """Shared param plumbing for RNN/LSTM/GRU cells."""

    def __init__(self, hidden_size, gates, input_size,
                 i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._gates = gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(gates * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(gates * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(gates * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(gates * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        if self.i2h_weight._deferred_init:
            self.i2h_weight.shape = (self._gates * self._hidden_size,
                                     x.shape[-1])
            self._input_size = x.shape[-1]


class RNNCell(_BaseGatedCell):
    """Elman RNN cell: h' = act(W x + b + R h + b_R)
    (reference: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(hidden_size, 1, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, prefix, params)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(_BaseGatedCell):
    """LSTM cell, gates i,f,g,o (reference: rnn_cell.py LSTMCell)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(hidden_size, 4, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, prefix, params)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        in_gate, forget_gate, in_transform, out_gate = F.SliceChannel(
            gates, num_outputs=4, axis=-1)
        in_gate = F.sigmoid(in_gate)
        forget_gate = F.sigmoid(forget_gate)
        in_transform = F.tanh(in_transform)
        out_gate = F.sigmoid(out_gate)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(_BaseGatedCell):
    """GRU cell, cuDNN variant, gates r,z,n (reference: rnn_cell.py GRUCell)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(hidden_size, 3, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, prefix, params)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        new = F.tanh(i2h_n + reset * h2h_n)
        next_h = (1.0 - update) * new + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells, feeding each output to the next
    (reference: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell, str(len(self._children)))

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[pos:pos + n]
            pos += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        return RecurrentCell.unroll(self, length, inputs,
                                    begin_state=begin_state, layout=layout,
                                    merge_outputs=merge_outputs,
                                    valid_length=valid_length)

    def forward(self, inputs, states):
        return self.__call__(inputs, states)


class DropoutCell(HybridRecurrentCell):
    """Dropout on cell outputs (reference: rnn_cell.py DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (reference: ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(), params=None)
        self.base_cell = base_cell
        self.register_child(base_cell, "base_cell")

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py ZoneoutCell)."""

    def _alias(self):
        return "zoneout"

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        super().__init__(base_cell)
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        po, ps = self._zoneout_outputs, self._zoneout_states

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(po, next_output), next_output, prev_output) \
            if po != 0.0 else next_output
        new_states = [F.where(mask(ps, ns), ns, os) for ns, os in
                      zip(next_states, states)] if ps != 0.0 else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds inputs to cell outputs (reference: rnn_cell.py ResidualCell)."""

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells over the sequence in opposite directions
    (reference: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            seq = [nd.squeeze(s, axis=axis) for s in
                   nd.split(inputs, num_outputs=length, axis=axis)] \
                if length > 1 else [nd.squeeze(inputs, axis=axis)]
        else:
            seq = list(inputs)
        batch_size = seq[0].shape[layout.find("N") - (1 if axis == 0 else 0)]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info())
        inner_layout = "NTC" if axis == 1 else "TNC"
        l_outputs, l_states = l_cell.unroll(
            length, seq, begin_state[:n_l], layout=inner_layout,
            merge_outputs=False, valid_length=valid_length)

        def _rev(step_list):
            """Reverse per-sample by valid_length so padding never enters the
            reverse recurrence (reference: rnn_cell.py BidirectionalCell uses
            SequenceReverse the same way)."""
            if valid_length is None:
                return list(reversed(step_list))
            stacked = nd.stack(*step_list, axis=0)  # time-major
            rev = nd.SequenceReverse(stacked, valid_length,
                                     use_sequence_length=True)
            return [nd.squeeze(s, axis=0) for s in
                    nd.split(rev, num_outputs=length, axis=0)] \
                if length > 1 else [nd.squeeze(rev, axis=0)]

        r_outputs, r_states = r_cell.unroll(
            length, _rev(seq), begin_state[n_l:], layout=inner_layout,
            merge_outputs=False, valid_length=valid_length)
        outputs = [nd.concat(l, r, dim=-1) for l, r in
                   zip(l_outputs, _rev(r_outputs))]
        if merge_outputs is None or merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
