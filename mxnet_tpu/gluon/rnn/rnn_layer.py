"""Fused recurrent layers: gluon.rnn.RNN / LSTM / GRU.

Reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` — thin wrappers over the
fused ``RNN`` op (here: ops/rnn.py lax.scan kernel), keeping per-layer
``{l}{i2h,h2h}_{weight,bias}`` parameters that are packed into the flat
cuDNN-layout vector at forward, so parameter names and shapes match the
reference's checkpoints.
"""
from __future__ import annotations

import numpy as np

from ... import initializer as init_mod
from ... import ndarray as nd
from ..block import HybridBlock
from ..parameter import Parameter


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), "invalid layout %r" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        from ...ops.rnn import _GATES
        self._gates = _GATES[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][:self._dir]:
                    self._register_param(
                        "%s%d_i2h_weight" % (j, i), (ng * nh, ni),
                        i2h_weight_initializer)
                    self._register_param(
                        "%s%d_h2h_weight" % (j, i), (ng * nh, nh),
                        h2h_weight_initializer)
                    self._register_param(
                        "%s%d_i2h_bias" % (j, i), (ng * nh,),
                        i2h_bias_initializer)
                    self._register_param(
                        "%s%d_h2h_bias" % (j, i), (ng * nh,),
                        h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, initializer):
        p = self.params.get(name, shape=shape, init=initializer,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        mapping = "{0} -> {1}".format(
            self._input_size if self._input_size else None, self._hidden_size)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_param_shapes(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        if self._input_size == 0:
            self._input_size = ni
            ng, nh = self._gates, self._hidden_size
            for i in range(self._num_layers):
                for j in ["l", "r"][:self._dir]:
                    p = getattr(self, "%s%d_i2h_weight" % (j, i))
                    if p._deferred_init:
                        p.shape = (ng * nh, ni)
                ni = nh * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent state(s) (reference: rnn_layer.py begin_state)."""
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs)
                          if "shape" in info else func(**kwargs))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if not isinstance(states, (list, tuple)):
            states = [states]

        flat = self._pack_params(F, params)
        args = [inputs, flat] + list(states)
        outs = F.RNN(*args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        if self._mode == "lstm":
            out, h, c = outs
            out_states = [h, c]
        else:
            out, h = outs
            out_states = [h]
        if self._layout == "NTC":
            out = F.swapaxes(out, dim1=0, dim2=1)
        return out if skip_states else (out, out_states)

    def _pack_params(self, F, params):
        """Concat per-layer parameters into the cuDNN flat layout
        (all weights layer-major, then all biases) — XLA fuses the concat
        into the consuming matmuls."""
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(F.reshape(params["%s%d_i2h_weight" % (j, i)],
                                    shape=(-1,)))
                ws.append(F.reshape(params["%s%d_h2h_weight" % (j, i)],
                                    shape=(-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(params["%s%d_i2h_bias" % (j, i)])
                bs.append(params["%s%d_h2h_bias" % (j, i)])
        return F.concat(*(ws + bs), dim=0)


class RNN(_RNNLayer):
    """Vanilla multi-layer RNN with relu/tanh (reference: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU, cuDNN variant (reference: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
