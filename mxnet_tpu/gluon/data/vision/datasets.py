"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py).

Download plumbing is kept (get_repo_file_url via gluon/utils) but these all
work offline from a pre-populated ``root`` directory — the normal mode in
an air-gapped TPU pod.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ... import utils as _gutils
from .... import ndarray as nd
from .... import recordio as _recordio
from ..dataset import ArrayDataset, Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files (reference: datasets.py MNIST; format parity
    with src/io/iter_mnist.cc)."""

    _base_files = {
        True: ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        False: ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _find(self, fname):
        for cand in (fname, fname[:-3]):  # allow unzipped
            p = os.path.join(self._root, cand)
            if os.path.isfile(p):
                return p
        raise FileNotFoundError(
            "%s not found under %s (no network egress; place the idx files "
            "there manually)" % (fname, self._root))

    def _get_data(self):
        img_file, lab_file = self._base_files[self._train]
        img_path = self._find(img_file)
        lab_path = self._find(lab_file)

        def _open(p):
            return gzip.open(p, "rb") if p.endswith(".gz") else open(p, "rb")

        with _open(lab_path) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
        with _open(img_path) as fin:
            _, n, rows, cols = struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(n, rows, cols, 1)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the binary batches (reference: datasets.py CIFAR10)."""

    _archive = "cifar-10-binary.tar.gz"
    _train_names = ["data_batch_%d.bin" % i for i in range(1, 6)]
    _test_names = ["test_batch.bin"]
    _ncats = 1

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, path):
        with open(path, "rb") as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        row = 3072 + self._ncats
        raw = raw.reshape(-1, row)
        data = raw[:, self._ncats:].reshape(-1, 3, 32, 32)
        return data.transpose(0, 2, 3, 1), raw[:, self._ncats - 1].astype(np.int32)

    def _locate(self, name):
        for cand in (os.path.join(self._root, name),
                     os.path.join(self._root, "cifar-10-batches-bin", name),
                     os.path.join(self._root, "cifar-100-binary", name)):
            if os.path.isfile(cand):
                return cand
        # try extracting a local archive copy
        arc = os.path.join(self._root, self._archive)
        if os.path.isfile(arc):
            with tarfile.open(arc) as tf:
                tf.extractall(self._root)
            return self._locate(name)
        raise FileNotFoundError(
            "%s not found under %s (no network egress; place the CIFAR "
            "binaries there manually)" % (name, self._root))

    def _get_data(self):
        names = self._train_names if self._train else self._test_names
        data, label = zip(*[self._read_batch(self._locate(n)) for n in names])
        self._data = nd.array(np.concatenate(data), dtype=np.uint8)
        self._label = np.concatenate(label)


class CIFAR100(CIFAR10):
    _archive = "cifar-100-binary.tar.gz"
    _train_names = ["train.bin"]
    _test_names = ["test.bin"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._ncats = 2
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _read_batch(self, path):
        with open(path, "rb") as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        row = 3072 + 2
        raw = raw.reshape(-1, row)
        data = raw[:, 2:].reshape(-1, 3, 32, 32)
        lab = raw[:, 1 if self._fine else 0].astype(np.int32)
        return data.transpose(0, 2, 3, 1), lab


class ImageRecordDataset(RecordFileDataset):
    """Dataset over an image RecordIO file
    (reference: datasets.py ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import image as _img
        record = super().__getitem__(idx)
        header, img = _recordio.unpack(record)
        decoded = _img.imdecode(img, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(decoded, label)
        return decoded, label


class ImageFolderDataset(Dataset):
    """folder/label/img.jpg layout (reference: datasets.py
    ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image as _img
        img = _img.imread(self.items[idx][0], flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
