"""Vision transforms (reference: python/mxnet/gluon/data/vision/transforms.py).

Transforms are Blocks operating per-sample on HWC uint8/float NDArrays;
the heavy per-pixel work (resize/crop) runs through cv2 on the host — see
the TPU-first note in image/image.py.
"""
from __future__ import annotations

import random

import numpy as np

from .... import image as _image
from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting", "RandomGray"]


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Sequential):
    """Sequentially compose transforms (reference: transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return nd.array(_np(x).astype(self._dtype), dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference: ToTensor)."""

    def forward(self, x):
        a = _np(x).astype(np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return nd.array(a)


class Normalize(Block):
    """(x - mean) / std per channel on CHW float tensors."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        return nd.array((_np(x) - self._mean) / self._std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        if isinstance(self._size, int):
            if self._keep:
                return _image.resize_short(x, self._size, self._interpolation)
            w = h = self._size
        else:
            w, h = self._size
        return _image.imresize(x, w, h, self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interpolation = interpolation

    def forward(self, x):
        return _image.center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        return _image.random_size_crop(x, self._size, self._scale,
                                       self._ratio, self._interpolation)[0]


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if random.random() < 0.5:
            return nd.array(_np(x)[:, ::-1].copy(), dtype=_np(x).dtype)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if random.random() < 0.5:
            return nd.array(_np(x)[::-1].copy(), dtype=_np(x).dtype)
        return x


class _JitterBlock(Block):
    def __init__(self, aug):
        super().__init__()
        self._aug = aug

    def forward(self, x):
        return self._aug(x)


class RandomBrightness(_JitterBlock):
    def __init__(self, brightness):
        super().__init__(_image.BrightnessJitterAug(brightness))


class RandomContrast(_JitterBlock):
    def __init__(self, contrast):
        super().__init__(_image.ContrastJitterAug(contrast))


class RandomSaturation(_JitterBlock):
    def __init__(self, saturation):
        super().__init__(_image.SaturationJitterAug(saturation))


class RandomHue(_JitterBlock):
    def __init__(self, hue):
        super().__init__(_image.HueJitterAug(hue))


class RandomColorJitter(_JitterBlock):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        aug = _image.SequentialAug(
            ([_image.ColorJitterAug(brightness, contrast, saturation)]
             if (brightness or contrast or saturation) else []) +
            ([_image.HueJitterAug(hue)] if hue else []))
        super().__init__(aug)


class RandomLighting(_JitterBlock):
    def __init__(self, alpha):
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        super().__init__(_image.LightingAug(alpha, eigval, eigvec))


class RandomGray(_JitterBlock):
    def __init__(self, p=0.5):
        super().__init__(_image.RandomGrayAug(p))
