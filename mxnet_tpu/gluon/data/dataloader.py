"""DataLoader: minibatch loader with multiprocessing workers.

Reference: ``python/mxnet/gluon/data/dataloader.py`` — worker processes
decode/transform samples and ship batches back through shared-memory
NDArrays (cpu_shared_storage_manager).

TPU-native design: workers produce *numpy* batches (pickled through the
Pool pipe — host RAM is not the bottleneck; JPEG decode/augment is), and
the main process does one ``jax.device_put`` per batch, which jax overlaps
with TPU compute.  ``num_workers=0`` is a synchronous in-process loop.
"""
from __future__ import annotations

import multiprocessing as mp
import os

import numpy as np

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    arr = np.asarray(data)
    return nd.array(arr, dtype=arr.dtype)


def _np_batchify(data):
    """Worker-side batchify to numpy (crosses the process boundary)."""
    if isinstance(data[0], NDArray):
        return np.stack([d.asnumpy() for d in data])
    if isinstance(data[0], tuple):
        return [_np_batchify(list(i)) for i in zip(*data)]
    return np.asarray(data)


default_mp_batchify_fn = _np_batchify

_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(samples):
    return _np_batchify([_worker_dataset[i] for i in samples])


def _to_nd(batch):
    if isinstance(batch, list):
        return [_to_nd(b) for b in batch]
    return nd.array(batch, dtype=batch.dtype)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_sampler is mutually exclusive with "
                             "batch_size/shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn
        self._prefetch = max(0, prefetch or 2 * self._num_workers)
        self._thread_pool = thread_pool
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(self._num_workers)
            else:
                # forkserver: fork() from a multithreaded jax process can
                # deadlock (the reference guards fork with engine stop/start
                # handlers, src/initialize.cc); the forkserver parent has no
                # jax threads, and the dataset ships to workers via pickle
                ctx = mp.get_context("forkserver")
                self._pool = ctx.Pool(self._num_workers,
                                      initializer=_worker_init,
                                      initargs=(dataset,))

    def __iter__(self):
        if self._pool is None:
            batchify = self._batchify_fn or default_batchify_fn
            for batch in self._batch_sampler:
                yield batchify([self._dataset[i] for i in batch])
            return
        # pipelined: keep `prefetch` batches in flight (the ThreadedIter /
        # shared-mem pipeline analogue)
        batchify = self._batchify_fn or _worker_fn
        async_results = []
        it = iter(self._batch_sampler)

        def submit():
            try:
                batch = next(it)
            except StopIteration:
                return False
            if self._thread_pool:
                # threads share this process: pass the dataset explicitly
                # (a module global would be clobbered by a second loader)
                async_results.append(self._pool.apply_async(
                    _thread_worker_fn,
                    (self._dataset, batch, self._batchify_fn)))
            elif self._batchify_fn is not None:
                async_results.append(self._pool.apply_async(
                    _custom_worker_fn, (batch, self._batchify_fn)))
            else:
                async_results.append(self._pool.apply_async(_worker_fn,
                                                            (batch,)))
            return True

        for _ in range(self._prefetch or 1):
            if not submit():
                break
        # bounded waits (the SRC005 worker-loop discipline): a process-pool
        # worker lost to the OOM killer can orphan its AsyncResult, and a
        # bare .get() would then hang this loop forever.  Poll with a
        # timeout and give up loudly at a total deadline instead.
        deadline_s = float(os.environ.get("MXTPU_DATALOADER_TIMEOUT", "600"))
        while async_results:
            res = async_results.pop(0)
            waited = 0.0
            while True:
                try:
                    out = res.get(timeout=5.0)
                    break
                except mp.TimeoutError:
                    waited += 5.0
                    if waited >= deadline_s:
                        raise RuntimeError(
                            "DataLoader batch not produced within %.0fs — "
                            "a pool worker likely died (OOM-killed?); "
                            "raise MXTPU_DATALOADER_TIMEOUT if the "
                            "dataset is genuinely that slow" % deadline_s)
            submit()
            yield _to_nd(out) if self._batchify_fn is None else out

    def __len__(self):
        return len(self._batch_sampler)

    def shutdown(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


def _custom_worker_fn(samples, batchify_fn):
    return batchify_fn([_worker_dataset[i] for i in samples])


def _thread_worker_fn(dataset, samples, batchify_fn):
    fn = batchify_fn or _np_batchify
    return fn([dataset[i] for i in samples])
