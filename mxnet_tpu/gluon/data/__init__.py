"""`gluon.data` (reference: python/mxnet/gluon/data/)."""
from . import vision
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .dataloader import DataLoader
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
