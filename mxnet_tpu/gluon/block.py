"""Gluon Block / HybridBlock and the CachedOp (hybridized) executor.

Reference: ``python/mxnet/gluon/block.py:126`` (Block), ``:669``
(HybridBlock), ``hybridize:830``; CachedOp ``src/imperative/cached_op.cc:94``
with static/dynamic memory planning (``:684,756``).

TPU-native design: ``hybridize()`` compiles the block's forward into ONE
``jax.jit`` program per (input shapes/dtypes, train-flag) key — XLA's fusion
and buffer assignment replace the reference's nnvm graph caching and
PlanMemory pass.  Tracing runs the same eager Python ``hybrid_forward`` with
NDArrays wrapping tracers, so there is no separate symbolic dialect.
Mutable aux states (BatchNorm moving stats) touched during tracing are
captured via the NDArray mutation tracker and returned as extra jit outputs,
then written back — the functional analogue of FMutateInputs
(op_attr_types.h).  RNG inside the trace draws from a per-call key argument
(see mxnet_tpu/_rng.py), keeping the compiled program pure.
"""
from __future__ import annotations

import re

import numpy as np

import jax

from .. import autograd, _rng
from .. import ndarray as nd
from ..ndarray import NDArray
from ..ndarray import ndarray as _ndmod
from .parameter import Parameter, ParameterDict, DeferredInitializationError


class _BlockScope:
    _current = None
    _global_counter = {}

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = _BlockScope._current
        if current is None:
            if prefix is None:
                # global NameManager analogue (reference: python/mxnet/name.py)
                count = _BlockScope._global_counter.get(hint, 0)
                _BlockScope._global_counter[hint] = count + 1
                prefix = "%s%d_" % (hint, count) if hint else ""
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        # empty-prefix blocks are naming-transparent: the parent scope stays
        # active so sibling counters continue (reference: block.py:73-75)
        if self._block._empty_prefix:
            return self
        self._old_scope = _BlockScope._current
        _BlockScope._current = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _BlockScope._current = self._old_scope


def _in_cached_trace():
    return bool(_ndmod._MUTATION_TRACKERS)


class Block:
    """Base building block (reference: gluon/block.py:126)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        from .. import initializer
        self.collect_params().initialize(init or initializer.Uniform(), ctx,
                                         verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self._reg_params.items():
            param.cast(dtype)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, format="mxtpu"):
        """Reference: gluon/block.py:313.  format="mxnet" writes the
        reference dmlc-stream .params layout."""
        params = self._collect_params_with_prefix()
        nd.save(filename, {k: v.data() for k, v in params.items()
                           if v._data is not None}, format=format)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False):
        """Reference: gluon/block.py:355."""
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise IOError("missing parameter %r in %s" % (name, filename))
        for name, data in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise IOError("unknown parameter %r in %s" % (name, filename))
                continue
            params[name].set_data(data)

    save_params = save_parameters
    load_params = load_parameters

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def summary(self, *inputs):
        from ..visualization import block_summary
        return block_summary(self, *inputs)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __repr__(self):
        s = "{name}(\n{modstr}\n)" if self._children else "{name}()"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(str(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    first = lines.pop(0)
    return "\n".join([first] + [(num_spaces * " ") + line for line in lines])


class CachedOp:
    """Hybrid-graph executor: one jit program per input signature.

    Reference: src/imperative/cached_op.cc:94 (Forward:834 →
    StaticForward/DynamicForward, Backward:1046).  The signature→compiled
    cache replaces the reference's static/dynamic memory plans: XLA buffer
    assignment handles allocation; jax.vjp over the same traced callable
    provides Backward.
    """

    def __init__(self, block):
        self._block = block
        self._cache = {}
        self._remat = bool(getattr(block, "_remat", False))

    def cache_keys(self):
        """The jit-cache keys compiled so far: one per (input shapes/dtypes,
        train flag, kwargs) signature.  Stable set == no recompiles."""
        return set(self._cache.keys())

    def cache_size(self):
        return len(self._cache)

    def _make_body(self, params, param_names, kwargs, train):
        block = self._block

        def body(param_vals, input_vals, rng_key):
            """Pure function of (params, inputs, key) -> outputs + mutated aux."""
            mutations = []
            wrapped_inputs = [NDArray(v) for v in input_vals]
            _ndmod._MUTATION_TRACKERS.append(
                lambda obj, val: mutations.append((obj, val)))
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(train)
            saved = {}
            try:
                with _rng.trace_scope(rng_key):
                    for name, val in zip(param_names, param_vals):
                        saved[name] = params[name]._data._data
                        params[name]._data._data = val
                    try:
                        out = block.hybrid_forward_wrapper(*wrapped_inputs,
                                                           **kwargs)
                    finally:
                        mut_ids, mut_vals = [], []
                        for obj, new_val in mutations:
                            for name in param_names:
                                if params[name]._data is obj:
                                    mut_ids.append(name)
                                    mut_vals.append(new_val)
                                    break
                        for name in param_names:
                            params[name]._data._data = saved[name]
            finally:
                _ndmod._MUTATION_TRACKERS.pop()
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
            single = not isinstance(out, (list, tuple))
            outs = [out] if single else list(out)
            body.mut_ids = mut_ids        # static side-channel, set at trace
            body.single = single
            return tuple(o._data for o in outs) + tuple(mut_vals)

        body.mut_ids = None
        body.single = True
        return body

    def __call__(self, params, inputs, train, kwargs):
        key = (
            tuple((tuple(i.shape), str(i.dtype)) for i in inputs),
            bool(train),
            tuple(sorted(kwargs.items())) if kwargs else (),
        )
        entry = self._cache.get(key)
        if entry is None:
            param_names = list(params.keys())
            body = self._make_body(params, param_names, kwargs, train)
            fn = jax.checkpoint(body) if (self._remat and train) else body
            entry = {"body": body, "jitted": jax.jit(fn),
                     "param_names": param_names}
            self._cache[key] = entry

        body = entry["body"]
        param_nds = [params[n].data() for n in entry["param_names"]]
        param_vals = tuple(p._data for p in param_nds)
        input_vals = tuple(i._data for i in inputs)
        rng_key = _rng.next_key()

        if autograd.is_recording():
            jfn = entry["jitted"]

            def fwd(pv, iv):
                return jfn(pv, iv, rng_key)

            all_out, vjp_fn = jax.vjp(fwd, param_vals, input_vals)

            def node_vjp(cotangents):
                pg, ig = vjp_fn(tuple(cotangents))
                return list(pg) + list(ig)

            node = autograd.record_op(node_vjp, param_nds + list(inputs),
                                      list(all_out))
        else:
            all_out = entry["jitted"](param_vals, input_vals, rng_key)
            node = None

        n_mut = len(body.mut_ids or ())
        n_out = len(all_out) - n_mut
        out_nds = [NDArray(o) for o in all_out[:n_out]]
        if node is not None:
            for i, o in enumerate(out_nds):
                o._entry = (node, i)
        for name, val in zip(body.mut_ids or (), all_out[n_out:]):
            params[name]._data._set_data(val)
        return out_nds[0] if body.single else out_nds


class HybridBlock(Block):
    """Block that can be hybridized into a jit-compiled CachedOp
    (reference: gluon/block.py:669)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  remat=False, **kwargs):
        """remat=True rematerializes this block's forward in the backward
        pass (jax.checkpoint) — the MXNET_BACKWARD_DO_MIRROR /
        docs/faq/env_var.md memory-mirroring analogue: sublinear activation
        memory for extra FLOPs."""
        self._active = active
        self._cached_op = None
        self._remat = remat
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_param_shapes(self, *args):
        """Resolve deferred parameter shapes from input shapes.
        Layers with deferred params override this (reference: generic
        infer_shape pass; here each layer knows its own rule)."""

    def hybrid_forward_wrapper(self, *args, **kwargs):
        pkw = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd, *args, **pkw, **kwargs)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def forward(self, *args, **kwargs):
        if any(p._deferred_init for p in self._reg_params.values()):
            self.infer_param_shapes(*args)
            for p in self._reg_params.values():
                if p._deferred_init:
                    p._finish_deferred_init()
        if self._active and not _in_cached_trace():
            if any(p._deferred_init
                   for p in self.collect_params().values()):
                # children still deferred: one eager pass resolves shapes
                # (the reference runs infer_shape over the graph instead)
                with autograd.pause(train_mode=autograd.is_training()):
                    self.hybrid_forward_wrapper(*args, **kwargs)
            if self._cached_op is None:
                self._cached_op = CachedOp(self)
            all_params = self._collect_all_reg_params()
            return self._cached_op(all_params, list(args),
                                   autograd.is_training(), kwargs)
        return self.hybrid_forward_wrapper(*args, **kwargs)

    def jit_cache_keys(self):
        """Jit-cache keys across this block and its hybridized children
        (reference: the CachedOp signature cache, cached_op.cc:94).  A
        serving ModelRunner snapshots this after warmup; any growth under
        traffic is a steady-state recompile."""
        keys = set()
        if self._cached_op is not None:
            keys |= {(self.name, k) for k in self._cached_op.cache_keys()}
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                keys |= child.jit_cache_keys()
        return keys

    def jit_cache_size(self):
        return len(self.jit_cache_keys())

    def _collect_all_reg_params(self):
        out = {}
        for p in self._reg_params.values():
            out[p.name] = p
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                out.update(child._collect_all_reg_params())
        return out

    def export(self, path, epoch=0):
        """Save graph JSON + params for deployment (reference: block.py:866).
        The params file uses arg:/aux: key prefixes like the reference's
        HybridBlock.export."""
        import json
        params = self._collect_params_with_prefix()
        arg_dict = {}
        for name, p in params.items():
            if p._data is not None:
                prefix = "aux:" if p.grad_req == "null" else "arg:"
                arg_dict[prefix + name] = p.data()
        nd.save("%s-%04d.params" % (path, epoch), arg_dict)
        sym = {"nodes": [{"op": "cached_op_subgraph", "name": self.name,
                          "params": sorted(params.keys())}],
               "format": "mxnet_tpu-0.1"}
        with open("%s-symbol.json" % path, "w") as f:
            json.dump(sym, f, indent=2)


class SymbolBlock(HybridBlock):
    """Run a loaded Symbol graph as a Gluon block
    (reference: gluon/block.py SymbolBlock)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._symbol = outputs
        self._sym_inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]

    def hybrid_forward(self, F, *args, **kwargs):
        from ..symbol import eval_symbol
        names = [i.name for i in self._sym_inputs]
        feed = dict(zip(names, args))
        out = eval_symbol(self._symbol, feed)
        return out
