"""Gluon Trainer (reference: ``python/mxnet/gluon/trainer.py:27`` —
_init_kvstore:153, step:217, allreduce_grads:245)."""
from __future__ import annotations

from .. import kvstore as kvs
from .. import optimizer as opt
from ..ndarray import NDArray
from .parameter import Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError("params must be a ParameterDict or list of Parameters")
        if not params:
            raise ValueError(
                "no parameters to optimize (reference Trainer raises on an "
                "empty ParameterDict too)")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError("invalid parameter %r" % (param,))
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kvstore = None
        self._kv_initialized = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise ValueError(
                    "optimizer_params must be None when optimizer is an instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = opt.get_updater(self._optimizer)

    def _init_kvstore(self):
        """Reference: trainer.py:153 — decide kvstore + update placement."""
        if self._kv_type is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kvs.create(self._kv_type) if isinstance(self._kv_type, str) \
                else self._kv_type
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                # single-copy parameters: local update is the fast path on TPU
                self._update_on_kvstore = False
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    kv.init(i, param.data())
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            self._kvstore = kv
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler else self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + update (reference: trainer.py:217)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Reference: trainer.py:245 — push(grad); pull(grad).  On one
        process this is the identity (one grad copy already); across hosts
        the kvstore lowers to a DCN psum."""
        if self._kvstore is None or self._kvstore.num_workers == 1:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore.push(i, param.grad(), priority=-i)
                if not self._update_on_kvstore:
                    self._kvstore.pull(i, param.grad(), priority=-i)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore and self._kvstore is not None:
                self._kvstore.push(i, param.grad(), priority=-i)
                self._kvstore.pull(i, param.data(), priority=-i)
            else:
                self._updaters(i, param.grad(), param.data())

    def update(self, batch_size, ignore_stale_grad=False):
        """Manual update after a custom allreduce (reference: trainer.py update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updaters.get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters.set_states(f.read())
