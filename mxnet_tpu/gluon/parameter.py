"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` — deferred initialization,
grad_req handling, per-context replication.  TPU-native difference: a
parameter holds ONE jax array (possibly sharded across the mesh by
``mxnet_tpu.parallel``) instead of the reference's per-GPU copies; Trainer's
allreduce collapses to XLA collectives.
"""
from __future__ import annotations

import numpy as np

from .. import autograd, initializer
from .. import ndarray as nd
from ..base import MXNetError, np_dtype
from ..context import Context, cpu, current_context
from ..ndarray import NDArray


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req if differentiable else "null"
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._stype = stype
        self._grad_stype = grad_stype
        self._ctx = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self._shape,
                                                      self.dtype)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(
            s1 in (0, s2) for s1, s2 in zip(self._shape, new_shape)
        ) and len(self._shape) == len(new_shape)
        if not unknown_ok:
            raise AssertionError(
                "cannot reset shape %s -> %s for %s" % (self._shape, new_shape,
                                                        self.name))
        self._shape = tuple(new_shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # single logical device; sharding handles the rest
        self._ctx = ctx
        if self._shape is None or 0 in self._shape:
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                "cannot initialize %s: shape unknown %s" % (self.name, self._shape))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = nd.empty(self._shape, ctx=ctx, dtype=self.dtype)
        chosen = init or self.init or default_init
        initializer.create(chosen)(initializer.InitDesc(self.name), data)
        self._init_impl(data)

    def _init_impl(self, data):
        self._data = data
        self._deferred_init = ()
        if self.grad_req != "null":
            self._data.attach_grad(self.grad_req)
            self._grad = self._data._grad

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init = self._deferred_init
        if self._shape is None or 0 in self._shape:
            raise DeferredInitializationError(
                "parameter %s has unknown shape %s" % (self.name, self._shape))
        self._finish_init(init, ctx, default_init)

    def _check_init(self):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "parameter %s deferred; run a forward pass first" % self.name)
            raise RuntimeError(
                "parameter %s not initialized; call initialize()" % self.name)

    def data(self, ctx=None):
        self._check_init()
        return self._data

    def list_data(self):
        self._check_init()
        return [self._data]

    def grad(self, ctx=None):
        self._check_init()
        if self._data._grad is None:
            raise RuntimeError("parameter %s has grad_req=null" % self.name)
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return [self._deferred_init[1]]
        self._check_init()
        return [self._data.context]

    def set_data(self, data):
        if not isinstance(data, NDArray):
            data = nd.array(data, dtype=self.dtype)
        if self._data is None:
            # loading into an uninitialized parameter: adopt the value
            # (reference allows load_parameters before initialize when
            # shapes are known)
            self.shape = tuple(data.shape)
            self._deferred_init = ()
            self._init_impl(nd.array(data, dtype=self.dtype))
            return
        self._data._set_data(data._data.astype(np_dtype(self.dtype)))

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            g = self._data._grad
            g._set_data(g._data * 0)

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data._set_data(self._data._data.astype(self.dtype))
            if had_grad:
                self._data.attach_grad(self.grad_req)
                self._grad = self._data._grad

    def var(self):
        from .. import symbol
        if self._var is None:
            self._var = symbol.var(self.name, shape=self._shape,
                                   dtype=self.dtype, lr_mult=self.lr_mult,
                                   wd_mult=self.wd_mult, init=self.init)
        return self._var

    def reset_ctx(self, ctx):
        self._ctx = ctx
        if self._data is not None:
            moved = self._data.as_in_context(ctx if not isinstance(ctx, (list, tuple)) else ctx[0])
            self._data._set_data(moved._data)


class Constant(Parameter):
    """Non-updating parameter (reference: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _Init(initializer.Initializer):
            def _init_weight(self_, _, arr):
                arr[:] = value.asnumpy()

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_Init())


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return "ParameterDict(%s)" % ", ".join(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None and param.shape is not None:
                    param.shape = v
                elif getattr(param, k, None) is None and v is not None:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("duplicate parameter %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        init = init or initializer.Uniform()
        for v in self.values():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = sum(w.copyto(cpu()) for w in block) / len(block)
            if not param.name.startswith(strip_prefix):
                raise ValueError("prefix %s not in param name %s"
                                 % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = {(restore_prefix + k): v for k, v in nd.load(filename).items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise IOError("parameter %s missing in file %s"
                                  % (name, filename))
        for name, val in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise IOError("unknown parameter %s in file %s"
                                  % (name, filename))
                continue
            self[name].set_data(val)
