"""KVStore: key→array store driving data-parallel training.

Reference: ``include/mxnet/kvstore.h:47-383``, ``src/kvstore/`` — types
local/device/nccl/dist_sync/dist_device_sync/dist_async chosen by string
(kvstore.cc:40-72), intra-node Comm reduce (comm.h), NCCL allreduce
(kvstore_nccl.h), ps-lite parameter server (kvstore_dist.h).

TPU-native design: the aggregation *API* (Init/Push/Pull/PullRowSparse/
set_optimizer/Barrier/rank) is preserved so Module/Trainer code ports
unchanged, but the transport collapses:

- ``local``/``device``/``nccl``/``tpu``: single-process store; pushed lists
  are summed with one fused jnp sum (the Comm/NCCL-tree analogue — on one
  chip XLA fuses it; across a mesh the parallel trainer lowers the same
  reduction to ``psum`` over ICI, see mxnet_tpu/parallel/).
- ``dist_sync``/``dist_device_sync``/``dist_async``/``tpu_dist``: multi-host
  via ``jax.distributed`` — every host holds a replica and the reduction
  rides a global-mesh psum (DCN across slices).  Single-process fallback
  (rank 0 of 1) keeps semantics identical so the nightly-style exact-sum
  tests run without a cluster.

The reference's server-side optimizer (``set_optimizer`` pickled to servers,
kvstore_dist_server.h:283) maps to running the updater at push time against
the stored weights — optimizer-state placement on the store is the TPU
analogue of PS state sharding.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from . import optimizer as opt
from .base import MXNetError, config
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["KVStore", "create"]

_DIST_TYPES = ("dist_sync", "dist_device_sync", "dist_async", "tpu_dist")


def _check_dist_env():
    """The cluster handshake happens at `import mxnet_tpu` (it must precede
    any backend initialization — see __init__.py).  If a launcher's env is
    present but the cluster never formed, degrading silently to
    rank-0-of-1 would train unsynchronized — fail loudly instead."""
    import os
    if jax.process_count() > 1:
        return
    if os.environ.get("JAX_COORDINATOR_ADDRESS") and \
            int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
        raise MXNetError(
            "distributed kvstore requested with JAX_NUM_PROCESSES=%s but "
            "the jax cluster has 1 process — the coordinator env must be "
            "set BEFORE `import mxnet_tpu` (tools/launch.py does this)"
            % os.environ["JAX_NUM_PROCESSES"])


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._compression = None
        self._compression_residuals = {}
        self._is_dist = kv_type in _DIST_TYPES
        self._ps_client = None
        self._ps_server = None
        if self._is_dist:
            _check_dist_env()
            self._rank = jax.process_index()
            self._num_workers = jax.process_count()
        else:
            self._rank = 0
            self._num_workers = 1
        if kv_type == "dist_async" and self._num_workers > 1:
            self._start_ps()

    def _start_ps(self):
        """dist_async rides a host-side parameter server — async per-push
        application is what a collective cannot express (reference:
        kvstore_dist_server.h:285).  The server is either a dedicated
        ``DMLC_ROLE=server`` rank (``DMLC_NUM_SERVER`` > 0 — spawned by
        ``tools/launch.py --num-servers``, crash-recoverable through its
        state dir) or an embedded thread on rank 0.  The elastic tier
        rides along: worker heartbeats feed the server watchdog
        (dead-worker key reassignment), pushes carry a per-store step so
        ``MXTPU_MAX_STALENESS`` can bound how stale a rejoining worker's
        gradients may be, and ``MXTPU_PS_STATE_DIR`` arms snapshot+WAL
        durability for the embedded server too (docs/resilience.md)."""
        import os
        from . import kvstore_ps
        from .kvstore_server import _durability_env
        host = os.environ.get("JAX_COORDINATOR_ADDRESS",
                              "127.0.0.1:0").split(":")[0]
        port = int(os.environ.get("MXTPU_PS_PORT", "0"))
        if not port:
            raise MXNetError(
                "dist_async needs MXTPU_PS_PORT (tools/launch.py sets it)")
        hb_interval = float(os.environ.get("MXTPU_HEARTBEAT_INTERVAL_S",
                                           "2.0"))
        hb_timeout = float(os.environ.get("MXTPU_HEARTBEAT_TIMEOUT_S",
                                          str(hb_interval * 5)))
        staleness = os.environ.get("MXTPU_MAX_STALENESS")
        num_servers = int(os.environ.get("DMLC_NUM_SERVER", "0"))
        if self._rank == 0 and num_servers == 0:
            # no dedicated server rank: rank 0 hosts the PS in-process
            state_dir, snapshot_every, keep = _durability_env()
            self._ps_server = kvstore_ps.PSServer(
                port=port, num_workers=self._num_workers,
                heartbeat_timeout_s=hb_timeout if hb_interval > 0 else None,
                max_staleness=int(staleness) if staleness else None,
                state_dir=state_dir, snapshot_every=snapshot_every,
                snapshot_keep=keep)
        self._ps_client = kvstore_ps.PSClient(host, port, rank=self._rank)
        self._push_step = 0
        if hb_interval > 0:
            from . import telemetry as _tele
            self._ps_client.start_heartbeat(
                hb_interval, step_fn=lambda: self._push_step,
                phase_fn=_tele.dominant_phase_or_none)

    # -- identity ----------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    @property
    def has_updater(self):
        """True when a store-side updater/optimizer is set (public surface
        so wrappers/duck-typed stores can be validated without reaching
        into private attributes)."""
        return self._updater is not None

    @property
    def compression(self):
        """The active gradient-compression config dict, or None."""
        return self._compression

    # -- core ops ----------------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            arr = v if isinstance(v, NDArray) else nd.array(v)
            self._store[k] = arr
            if self._ps_client is not None:
                import numpy as _np
                self._ps_client.init_array(
                    k, _np.asarray(arr.asnumpy(), _np.float32))

    def _merge(self, vlist):
        """Sum a list of same-key arrays (Comm::Reduce analogue, comm.h:451)."""
        if len(vlist) == 1:
            merged = vlist[0]
        else:
            from .ndarray.sparse import RowSparseNDArray
            if isinstance(vlist[0], RowSparseNDArray):
                merged = vlist[0]
                for v in vlist[1:]:
                    merged = merged + v
                return merged
            acc = vlist[0]._data
            for v in vlist[1:]:
                acc = acc + v._data
            merged = NDArray(acc)
        return merged

    def push(self, key, value, priority=0):
        keys, values = _key_value(key, value, allow_list_values=True)
        for k, v in zip(keys, values):
            vlist = v if isinstance(v, (list, tuple)) else [v]
            merged = self._merge(list(vlist))
            if self._ps_client is not None:
                self._ps_push(k, merged)
                continue
            if self._compression is not None:
                merged = self._compress(k, merged)
                if self._is_dist and self._num_workers > 1:
                    # compressed wire path: all-gather the packed 2-bit
                    # payloads (16x narrower than an fp32 psum), decode and
                    # sum locally (reference: gradient_compression.h)
                    merged = _cross_process_sum_packed(
                        merged, self._compression["threshold"])
            elif self._is_dist and self._num_workers > 1:
                merged = _cross_process_sum(merged)
            stored = self._store.get(k)
            if stored is None:
                raise MXNetError("key %r not initialized" % (k,))
            if self._updater is not None:
                self._updater(k, merged, stored)
            else:
                from .ndarray.sparse import RowSparseNDArray
                if isinstance(merged, RowSparseNDArray):
                    merged = merged.todense()
                stored._set_data(merged._data)

    def _ps_push(self, k, merged):
        """Async push: ships the gradient to the PS, which applies it
        immediately — no cross-worker rendezvous of any kind.  EVERY
        wire form (dense, rsp, 2bit) carries the worker's step so the
        bounded-staleness gate sees compressed/sparse pushes too."""
        import numpy as _np
        from .ndarray.sparse import RowSparseNDArray
        from . import kvstore_ps
        self._push_step += 1
        if isinstance(merged, RowSparseNDArray):
            payload = (_np.asarray(merged.indices.asnumpy(), _np.int64),
                       _np.asarray(merged.data.asnumpy(), _np.float32),
                       tuple(merged.shape))
            send = lambda: self._ps_client.request(
                "push", k, "rsp", payload, self._push_step)
        elif self._compression is not None:
            # compress once: error feedback mutates the residuals, so a
            # staleness retry re-sends the same packed payload
            q = self._compress(k, merged)
            thr = self._compression["threshold"]
            packed, shape = kvstore_ps.pack_2bit(q.asnumpy(), thr)
            send = lambda: self._ps_client.request(
                "push", k, "2bit", (packed, shape, thr), self._push_step)
        else:
            arr = _np.asarray(merged.asnumpy(), _np.float32)
            send = lambda: self._ps_client.push_array(
                k, arr, step=self._push_step)
        try:
            send()
        except kvstore_ps.StaleWorkerError as e:
            # bounded-staleness rejoin: this worker lagged the fleet past
            # the bound (it was dead/partitioned) — pull fresh state,
            # fast-forward the step clock, and re-send at the synced
            # clock.  Async PS semantics tolerate ONE bounded-stale
            # update; what the gate forbids is unbounded lag mixing in
            # silently (reference: SSP's bounded-staleness contract).
            import jax.numpy as _jnp
            fresh = self._ps_client.pull_array(k)
            self._store[k]._set_data(_jnp.asarray(fresh))
            self._push_step = e.max_step
            send()

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _key_value(key, out, allow_list_values=True)
        for k, o in zip(keys, outs):
            if self._ps_client is not None:
                import jax.numpy as _jnp
                arr = self._ps_client.pull_array(k)
                stored = self._store[k]
                stored._set_data(_jnp.asarray(arr))
            else:
                stored = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                dst._set_data(stored._data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference: kvstore.h:195
        PullRowSparse / kvstore_dist.h:665 EncodeRowSparseKey)."""
        from .ndarray.sparse import RowSparseNDArray
        keys, outs = _key_value(key, out, allow_list_values=True)
        if row_ids is None:
            raise ValueError("row_sparse_pull requires row_ids")
        rid_list = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in zip(keys, outs):
            stored = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            rids = rid_list if len(rid_list) == len(olist) else rid_list * len(olist)
            for dst, rid in zip(olist, rids):
                idx = jnp.unique(rid._data.astype(jnp.int64))
                rows = stored._data[idx.astype(jnp.int32)]
                if isinstance(dst, RowSparseNDArray):
                    dst.data = NDArray(rows)
                    dst.indices = NDArray(idx)
                    dst._shape = stored.shape
                else:
                    dst._set_data(stored._data)

    # -- optimizer / updater ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Run this optimizer on the store at push time (the reference pickles
        it to PS servers, python/mxnet/kvstore.py:443)."""
        if isinstance(optimizer, str):
            optimizer = opt.create(optimizer)
        if self._ps_client is not None:
            # shipped to the server exactly as the reference does
            self._ps_client.request("set_optimizer",
                                    pickle.dumps(optimizer))
            return
        # round-trip through pickle like the reference to guarantee the
        # optimizer is serializable for multi-host shipping
        optimizer = pickle.loads(pickle.dumps(optimizer))
        self._updater = opt.get_updater(optimizer)

    # -- gradient compression ---------------------------------------------
    def set_gradient_compression(self, compression_params):
        """2-bit stochastic quantization with error feedback
        (reference: src/kvstore/gradient_compression.h:52)."""
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise ValueError("unsupported compression type %r" % ctype)
        self._compression = {
            "threshold": float(compression_params.get("threshold", 0.5))}

    def _compress(self, key, merged):
        thr = self._compression["threshold"]
        resid = self._compression_residuals.get(key)
        g = merged._data
        if resid is None:
            resid = jnp.zeros_like(g)
        g = g + resid
        q = jnp.where(g >= thr, thr, jnp.where(g <= -thr, -thr, 0.0))
        self._compression_residuals[key] = g - q
        return NDArray(q)

    # -- cluster control ---------------------------------------------------
    def barrier(self):
        if self._ps_client is not None:
            self._ps_client.request("barrier")
            return
        if self._is_dist and self._num_workers > 1:
            _cross_process_sum(nd.ones((1,)))

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def get_num_dead_node(self, node_id=0):
        """PS liveness probe (reference: kvstore.h:339 — ps-lite heartbeat
        dead-node count).  The PS tracks worker connections: a rank whose
        socket closed without reconnecting counts as dead.  Non-PS types
        have no server to ask; jax.distributed surfaces failures as
        errors, so report 0 there."""
        if self._ps_client is not None:
            try:
                return int(self._ps_client.request("num_dead")[1])
            except (OSError, ConnectionError):
                return 1  # the server itself is unreachable
        return 0

    def _barrier_before_exit(self):
        self.barrier()


def _cross_process_sum(arr):
    """Sum across hosts over DCN (replaces ps-lite push/pull RPC).

    Builds a global array sharded one-slice-per-device over a ``hosts``
    mesh axis (each process contributes its local value on its first
    device, zeros elsewhere) and sums over that axis — XLA lowers it to a
    cross-host all-reduce and leaves the result replicated, so every host
    reads its own copy."""
    if jax.process_count() == 1:
        return arr
    from jax.sharding import NamedSharding, PartitionSpec as P

    local = arr._data
    mesh, my_dev, allsum = _allsum_program()
    shard = jax.device_put(local[None], my_dev)
    global_arr = jax.make_array_from_single_device_arrays(
        (jax.process_count(),) + tuple(local.shape),
        NamedSharding(mesh, P("hosts")), [shard])
    summed = allsum(global_arr)
    return NDArray(jnp.asarray(summed.addressable_data(0)))


import functools as _functools


@_functools.lru_cache(maxsize=1)
def _allsum_program():
    """One compiled cross-host reduce per cluster, over ONE device per
    process (zero-padding every local chip would move local_device_count x
    more data on the hottest dist path; a fresh lambda per push would
    defeat the jit cache)."""
    import numpy as _np
    from jax.sharding import NamedSharding, PartitionSpec as P
    per_proc = {}
    for d in jax.devices():
        if d.process_index not in per_proc or \
                d.id < per_proc[d.process_index].id:
            per_proc[d.process_index] = d
    devs = [per_proc[p] for p in sorted(per_proc)]
    mesh = jax.sharding.Mesh(_np.array(devs), ("hosts",))
    fn = jax.jit(_sum_axis0, out_shardings=NamedSharding(mesh, P()))
    return mesh, per_proc[jax.process_index()], fn


def _sum_axis0(a):
    return jnp.sum(a, axis=0)


def _cross_process_sum_packed(q_arr, threshold):
    """Compressed cross-host reduction: the wire carries packed 2-bit codes
    (uint8, 4 values/byte) via all-gather; every host decodes the other
    workers' payloads locally and sums in fp32.  Moves ~W x n/4 bytes vs
    the psum's ~4n (reference: gradient_compression.h wire format)."""
    import numpy as _np
    from . import kvstore_ps
    if jax.process_count() == 1:
        return q_arr
    from jax.sharding import NamedSharding, PartitionSpec as P
    packed, shape = kvstore_ps.pack_2bit(_np.asarray(q_arr.asnumpy()),
                                         threshold)
    mesh, my_dev, _ = _allsum_program()
    gather = _allgather_program()
    shard = jax.device_put(packed[None], my_dev)
    global_arr = jax.make_array_from_single_device_arrays(
        (jax.process_count(),) + packed.shape,
        NamedSharding(mesh, P("hosts")), [shard])
    gathered = _np.asarray(gather(global_arr).addressable_data(0))
    total = _np.zeros(shape, _np.float32)
    for w in range(gathered.shape[0]):
        total += kvstore_ps.unpack_2bit(gathered[w], shape, threshold)
    return NDArray(jnp.asarray(total))


@_functools.lru_cache(maxsize=1)
def _allgather_program():
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh, _, _ = _allsum_program()
    return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))


def _key_value(key, value, allow_list_values=False):
    if isinstance(key, (str, int)):
        return [key], [value]
    if value is None:
        return list(key), [None] * len(key)
    return list(key), list(value)


def create(name="local"):
    """Create a KVStore (reference: kvstore.cc:40-72 type dispatch).
    'nccl' and 'device' are accepted for script parity and map to the
    single-chip/tpu path; 'tpu_dist' is the native multi-host type."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    known = ("local", "device", "nccl", "tpu", "dist_sync", "dist_device_sync",
             "dist_async", "dist", "tpu_dist")
    if name not in known:
        raise MXNetError("unknown KVStore type %r (known: %s)" % (name, known))
    if name == "dist":
        name = "dist_sync"
    return KVStore(name)
