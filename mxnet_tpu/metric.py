"""Evaluation metrics (reference: ``python/mxnet/metric.py``, 1.4k LoC)."""
from __future__ import annotations

import math

import numpy as np

from .base import Registry
from .ndarray import NDArray

_REG = Registry("metric")


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if not shape:
        if len(labels) != len(preds):
            raise ValueError("labels/preds length mismatch: %d vs %d"
                             % (len(labels), len(preds)))


class EvalMetric:
    # lazy window bound: update_lazy keeps at most this many pending
    # batches device-resident before draining the oldest (their values
    # are long since computed by then, so the drain is ~free); bounds the
    # device memory the deferred labels/preds pin across a bulk window
    LAZY_MAX_PENDING = 64

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def update_lazy(self, labels, preds):
        """Non-blocking ``update``: park the (device-resident, lazy)
        labels/preds without fetching them — ``update`` calls ``asnumpy``
        per batch, a host sync that stalls the engine's run-ahead window
        every step.  The parked batches are drained (in order, so values
        are identical to eager updates) the next time anyone reads the
        metric — ``get``/``get_name_value``, i.e. a ``Speedometer`` tick
        or the epoch log: the flush boundaries."""
        self._lazy.append((labels, preds))
        while len(self._lazy) > self.LAZY_MAX_PENDING:
            labels, preds = self._lazy.pop(0)
            self.update(labels, preds)

    def _drain_lazy(self):
        pending, self._lazy = self._lazy, []
        for labels, preds in pending:
            self.update(labels, preds)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._lazy = []

    def get(self):
        self._drain_lazy()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


def register(klass):
    _REG.register(klass)
    return klass


def alias(*aliases):
    def deco(klass):
        _REG.alias(klass, *aliases)
        return klass
    return deco


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _REG.create(metric, *args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = metrics if metrics is not None else []

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        self._lazy = []
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        self._drain_lazy()
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(name if isinstance(name, list) else [name])
            values.extend(value if isinstance(value, list) else [value])
        return names, values


@register
@alias("acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(np.int32).ravel()
            label = label.astype(np.int32).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype(np.int32)
            topk = np.argsort(-pred, axis=1)[:, :self.top_k]
            for j in range(label.shape[0]):
                self.sum_metric += int(label[j] in topk[j])
            self.num_inst += label.shape[0]


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).ravel().astype(np.int32)
            if pred.ndim > 1:
                pred = pred.argmax(axis=1)
            pred = pred.ravel().astype(np.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self._stats = np.zeros(4)

    def reset(self):
        super().reset()
        self._stats = np.zeros(4)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).ravel().astype(np.int32)
            if pred.ndim > 1:
                pred = pred.argmax(axis=1)
            pred = pred.ravel().astype(np.int32)
            tp = ((pred == 1) & (label == 1)).sum()
            fp = ((pred == 1) & (label == 0)).sum()
            fn = ((pred == 0) & (label == 1)).sum()
            tn = ((pred == 0) & (label == 0)).sum()
            self._stats += np.array([tp, fp, fn, tn])
            tp, fp, fn, tn = self._stats
            denom = math.sqrt(max((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn), 1e-12))
            self.sum_metric = (tp * tn - fp * fn) / denom
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype(np.int32).ravel()
            pred = _as_np(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[np.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= np.log(np.maximum(1e-10, probs)).sum()
            num += label.size
        self.sum_metric += math.exp(loss / max(num, 1))
        self.num_inst += 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += np.abs(label.reshape(pred.shape) - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += ((label.reshape(pred.shape) - pred) ** 2).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)

    def get(self):
        self._drain_lazy()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
@alias("ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(np.int32)
            pred = _as_np(pred)
            prob = pred[np.arange(label.shape[0]), label]
            self.sum_metric += (-np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
@alias("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)
        self.eps = eps


@register
@alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred).ravel()
            self.sum_metric += np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = _as_np(pred).sum()
            self.sum_metric += loss
            self.num_inst += _as_np(pred).size


@register
class Torch(Loss):
    def __init__(self, name="torch", **kwargs):
        super().__init__(name, **kwargs)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False, **kwargs):
        if name is None:
            name = feval.__name__
        super().__init__(name, **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                num_inst, sum_metric = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np_metric(name=None, allow_extra_outputs=False):
    def deco(feval):
        return CustomMetric(feval, name, allow_extra_outputs)
    return deco
