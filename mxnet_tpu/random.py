"""``mx.random`` — global seeding + module-level samplers.

Reference: ``python/mxnet/random.py``.
"""
from __future__ import annotations

from . import _rng
from .ndarray import random as _nd_random


def seed(seed_state, ctx="all"):
    _rng.seed(seed_state)


uniform = _nd_random.uniform
normal = _nd_random.normal
randn = _nd_random.randn
poisson = _nd_random.poisson
exponential = _nd_random.exponential
gamma = _nd_random.gamma
multinomial = _nd_random.multinomial
shuffle = _nd_random.shuffle
randint = _nd_random.randint
negative_binomial = _nd_random.negative_binomial
generalized_negative_binomial = _nd_random.generalized_negative_binomial
