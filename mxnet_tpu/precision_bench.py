"""Host-only mixed-precision bench (the r05 subprocess pattern).

Run as ``python -m mxnet_tpu.precision_bench`` under
``JAX_PLATFORMS=cpu`` (bench.py's ``precision`` stage does, BEFORE
backend acquisition, so the keys stay live when the TPU is down).
Emits one JSON line:

- ``fused_loss_scaled_speedup_host``: REAL measured wall-time ratio of
  the unfused unscale+clip+update chain (per-parameter eqns, the
  ``jnp.where`` select-skip outside) vs the shipped fused kernel with
  the loss-scale reciprocal and finite flag riding the SMEM scalar
  block (``ops/fused_optimizer.py`` — unscale+clip+update+select-skip
  as ONE pass).  Gated ``higher`` in tools/bench_compare.py.
- ``bf16_modeled_hbm_ratio``: deterministic modeled peak-HBM ratio of
  the bf16 ZeRO-1 trainer vs its f32 twin from the
  ``bf16_zero1_train_step`` budget builder (0.66x measured = the 34%
  drop docs/precision.md claims).  Gated ``lower_abs``.
- ``bf16_convergence_delta``: max |loss_bf16 - loss_f32| over
  ``CONV_STEPS`` real trainer steps on the same data/seed — the
  mixed-precision trajectory must track full precision.  Gated
  ``lower_abs``.
- ``int8_kv_decode_tokens_per_sec_host``: greedy-decode throughput
  through a DecodeRunner over the int8 KV cache (quantized codes +
  per-page scales, dequant fused into the attention read).  Gated
  ``higher``.
- ``precision_numerics_ok``: 1.0 iff the fused loss-scaled update
  matches the unfused spelling within FLOAT_TOL, the skip path leaves
  params bitwise-untouched on an inf gradient, AND int8-KV greedy
  tokens agree with the f32-cache reference on >= 90% of generated
  tokens — gated at zero slack.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

FLOAT_TOL = 1e-5
BENCH_REPS = 40
NPAR, PSIZE = 96, 4096
CONV_STEPS = 20
DECODE_PROMPTS = 6
DECODE_NEW = 8


def _bench(fn, args, reps=BENCH_REPS):
    import jax
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _loss_scaled_update_bench(out):
    """Unfused unscale+clip+update chain vs the fused kernel with
    inv_scale/ok in the SMEM scalar block."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.ops import fused_optimizer as fo
    from mxnet_tpu.parallel.functional import functional_optimizer_update

    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4)
    rng = np.random.RandomState(7)
    ws = [jnp.asarray(rng.randn(PSIZE).astype("f")) for _ in range(NPAR)]
    gs = [jnp.asarray(rng.randn(PSIZE).astype("f")) for _ in range(NPAR)]
    ms = [jnp.asarray(rng.randn(PSIZE).astype("f")) for _ in range(NPAR)]
    wf, gf, mf = map(jnp.concatenate, (ws, gs, ms))
    lr = jnp.float32(0.1)
    inv = jnp.float32(1.0 / 1024.0)
    ok = jnp.float32(1.0)

    @jax.jit
    def unfused(ws, gs, ms, lr, inv, ok):
        outs = []
        for w, g, m in zip(ws, gs, ms):
            nw, nm = functional_optimizer_update(opt, 0, w, g * inv, m,
                                                 lr, 1)
            okb = ok > 0.0
            outs.append((jnp.where(okb, nw, w), jnp.where(okb, nm, m)))
        return [o[0] for o in outs], [o[1] for o in outs]

    @jax.jit
    def fused(wf, gf, mf, lr, inv, ok):
        return fo.fused_optimizer_update(opt, 0, wf, gf, mf, lr, 1,
                                         inv_scale=inv, ok=ok,
                                         interpret=True)

    nw_u, nm_u = unfused(ws, gs, ms, lr, inv, ok)
    jax.block_until_ready((nw_u, nm_u))
    nw_f, nm_f = fused(wf, gf, mf, lr, inv, ok)
    jax.block_until_ready((nw_f, nm_f))

    t_u = _bench(unfused, (ws, gs, ms, lr, inv, ok))
    t_f = _bench(fused, (wf, gf, mf, lr, inv, ok))
    out["fused_loss_scaled_unfused_ms"] = round(t_u * 1e3, 4)
    out["fused_loss_scaled_fused_ms"] = round(t_f * 1e3, 4)
    out["fused_loss_scaled_speedup_host"] = round(t_u / t_f, 3)

    err = max(float(jnp.max(jnp.abs(jnp.concatenate(nw_u) - nw_f))),
              float(jnp.max(jnp.abs(jnp.concatenate(nm_u) - nm_f))))
    # the skip contract: an inf gradient must leave w/m bitwise alone
    gbad = gf.at[0].set(np.inf)
    sw, sm = fused(wf, gbad, mf, lr, inv, jnp.float32(0.0))
    skipped_ok = bool((np.asarray(sw) == np.asarray(wf)).all()
                      and (np.asarray(sm) == np.asarray(mf)).all())
    return err, skipped_ok


def _convergence_bench(out):
    """bf16 vs f32 trainer loss trajectories, same seed/data."""
    from mxnet_tpu import init as mx_init
    from mxnet_tpu import ndarray as nd
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.trainer import DataParallelTrainer

    rng = np.random.RandomState(11)
    x = rng.randn(32, 16).astype(np.float32)
    y = rng.randint(0, 4, size=32).astype(np.int32)

    def losses(dtype):
        from mxnet_tpu import random as mx_random
        mx_random.seed(3)    # identical init for both arms
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(4))
        net.initialize(mx_init.Xavier(rnd_type="gaussian",
                                      magnitude=2.0))
        tr = DataParallelTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1},
                                 dtype=dtype)
        return [float(tr.step(nd.array(x), nd.array(y)))
                for _ in range(CONV_STEPS)]

    l32 = losses("float32")
    l16 = losses("bf16")
    delta = max(abs(a - b) for a, b in zip(l32, l16))
    out["bf16_convergence_delta"] = round(delta, 5)
    out["bf16_final_loss"] = round(l16[-1], 5)
    return l16[-1] < l16[0]    # it must actually be learning


def _int8_decode_bench(out):
    """Greedy decode through the int8 KV cache: tokens/sec + agreement
    with the f32-cache reference."""
    from mxnet_tpu.parallel.mesh import MeshPlan
    from mxnet_tpu.serving.decode import DecodeRunner
    from mxnet_tpu.transformer import TransformerLMConfig
    from mxnet_tpu.transformer.decode import DecodeProgram

    cfg = TransformerLMConfig(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64, seq_len=64)

    def runner(kv_dtype):
        prog = DecodeProgram(cfg, plan=MeshPlan(data=1), page_size=8,
                             kv_dtype=kv_dtype)
        params = prog.program.init_params(0)
        return DecodeRunner(prog, params, slots=2,
                            prefill_buckets=(8, 16), warmup=True)

    r8 = runner("int8")
    r32 = runner(None)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 64, size=rng.randint(3, 12)
                           ).astype(np.int32)
               for _ in range(DECODE_PROMPTS)]
    agree = total = 0
    for p in prompts:
        a = r8.generate(p, DECODE_NEW)
        b = r32.generate(p, DECODE_NEW)
        agree += int((np.asarray(a) == np.asarray(b)).sum())
        total += len(a)
    t0 = time.perf_counter()
    done = 0
    for p in prompts:
        done += len(r8.generate(p, DECODE_NEW))
    dt = time.perf_counter() - t0
    out["int8_kv_decode_tokens_per_sec_host"] = round(done / dt, 2)
    out["int8_kv_token_agreement"] = round(agree / total, 4)
    out["int8_kv_page_bytes"] = int(r8.program.bytes_per_page())
    return agree / total >= 0.9


def main():
    from mxnet_tpu.analysis.budget_models import bf16_zero1_train_step

    out = {}

    err, skipped_ok = _loss_scaled_update_bench(out)
    out["precision_numerics_max_err"] = float(err)

    # deterministic modeled ratio straight from the budget builder —
    # the same number the rc=2 gate pins
    _, _, shard = bf16_zero1_train_step()
    out["bf16_modeled_hbm_ratio"] = shard.extras["bf16_peak_hbm_ratio"]
    out["bf16_modeled_hbm_drop_pct"] = shard.extras[
        "bf16_modeled_hbm_drop_pct"]

    learning = _convergence_bench(out)
    int8_ok = _int8_decode_bench(out)

    out["precision_numerics_ok"] = 1.0 if (
        err <= FLOAT_TOL and skipped_ok and learning and int8_ok) else 0.0
    print(json.dumps(out))
    return 0 if out["precision_numerics_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
