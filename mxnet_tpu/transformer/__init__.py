"""mxnet_tpu.transformer — the 2-3D-mesh tensor/sequence-parallel tier.

A transformer LM trained end-to-end over a ``data × model × sequence``
:class:`~mxnet_tpu.parallel.mesh.MeshPlan` (docs/transformer.md):
Megatron-style column/row-sharded dense + vocab-parallel embeddings and
loss over ``model`` (arxiv 1810.09868's whole-program annotations,
spelled per replica), ring or Ulysses attention over ``sequence``
(``parallel/ring_attention.py`` — now trained with, not just shipped),
composing with the ZeRO-1 sharded optimizer of ``parallel/zero.py``
(arxiv 2004.13336) on the ``data`` axis.

Entry points::

    cfg = TransformerLMConfig(vocab_size=256, d_model=128, n_heads=8,
                              n_layers=4, d_ff=512, seq_len=1024)
    trainer = DataParallelTrainer(
        TransformerLM(cfg), None, "sgd", {"learning_rate": 0.1},
        mesh_plan=MeshPlan(data=2, model=2, sequence=2), zero=1)
    trainer.step(tokens, labels)          # (B, T) int32 global batches

The step is proven hardware-free by the ``tp_transformer_train_step``
budget model (STATIC_BUDGETS.json) whose runtime tape must match the
fixture — see ``analysis/budget_models.py`` and
``trainer.mesh_report()``.
"""
from .model import TransformerLM, TransformerLMConfig, MeshProgram
from . import layers, step

__all__ = ["TransformerLM", "TransformerLMConfig", "MeshProgram",
           "layers", "step"]
