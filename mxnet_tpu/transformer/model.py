"""Transformer LM over a 2-3D ``data × model × sequence`` mesh.

The model is spelled ONCE, per replica (the ``parallel/zero.py``
discipline): :meth:`MeshProgram.loss_replica` is a pure jax function
over LOCAL parameter shards and a LOCAL ``(B/Kd, T/Ks)`` token chunk,
with every cross-replica collective explicit.  The same function is

- jitted under ``shard_map`` by ``DataParallelTrainer(mesh_plan=...)``
  (the runtime), and
- traced with ``jax.make_jaxpr(axis_env=plan.axis_env())`` by
  ``trainer.mesh_report()`` and the ``tp_transformer_train_step``
  budget model (the hardware-free analysis),

so the executed program and the proven program can never drift.

Layer sharding (docs/transformer.md has the full table): token/output
embeddings vocab-parallel over ``model``; QKV column-parallel (heads
over ``model``); attention over the ``sequence`` axis via ring attention
(``parallel/ring_attention.py``) or Ulysses all-to-all when the local
head count divides; attention-out and MLP-down row-parallel with their
completing psum (the ``TP_ROW_PSUM`` seam); LayerNorms replicated.
Positions are global: each sequence rank offsets by
``axis_index("sequence") * T_local``.
"""
from __future__ import annotations

import numpy as _np

import jax

__all__ = ["TransformerLMConfig", "TransformerLM", "MeshProgram"]


class TransformerLMConfig:
    """Pinned-geometry transformer-LM hyperparameters.

    ``attention`` picks the sequence-parallel kernel: ``"ring"`` (K/V
    chunks rotate over ``ppermute`` — any head count, O(T/K) memory),
    ``"ulysses"`` (two all-to-alls swap sequence for head sharding —
    needs ``(n_heads / model) % sequence == 0``) or ``"auto"`` (Ulysses
    when the head count divides, else ring — the decision rule in
    docs/transformer.md).  With a collapsed sequence axis all three are
    plain local causal attention.
    """

    def __init__(self, vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                 d_ff=64, seq_len=64, attention="ring", init_seed=0,
                 init_scale=0.02, microbatches=None):
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.d_ff = int(d_ff)
        self.seq_len = int(seq_len)
        self.attention = str(attention)
        self.init_seed = int(init_seed)
        self.init_scale = float(init_scale)
        self.microbatches = (None if microbatches is None
                             else int(microbatches))
        if self.d_model % self.n_heads:
            raise ValueError("d_model %d must divide into n_heads %d"
                             % (self.d_model, self.n_heads))
        if self.attention not in ("ring", "ulysses", "auto"):
            raise ValueError("attention must be ring/ulysses/auto, got %r"
                             % (attention,))
        if self.microbatches is not None and self.microbatches < 1:
            raise ValueError("microbatches must be >= 1, got %r"
                             % (microbatches,))

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    def describe(self):
        return {k: getattr(self, k) for k in
                ("vocab_size", "d_model", "n_heads", "n_layers", "d_ff",
                 "seq_len", "attention", "init_seed", "microbatches")}


class TransformerLM:
    """The block handed to ``DataParallelTrainer(mesh_plan=...)`` — a
    thin config carrier implementing the mesh-program protocol the
    trainer's multi-axis tier consumes (``mesh_program(plan)``)."""

    def __init__(self, cfg):
        if not isinstance(cfg, TransformerLMConfig):
            cfg = TransformerLMConfig(**cfg)
        self.cfg = cfg

    def mesh_program(self, plan):
        return MeshProgram(self.cfg, plan)


def _attention_mode(cfg, plan):
    """The ring-vs-Ulysses decision rule (docs/transformer.md): Ulysses
    needs the LOCAL head count (heads already sharded over ``model``) to
    divide by the sequence-axis size; ``auto`` prefers it when legal
    (two all-to-alls move ~3x fewer bytes than a K-hop ring at moderate
    sequence lengths), ring otherwise."""
    if not plan.present("sequence"):
        return "local"
    h_local = cfg.n_heads // plan.size("model")
    divides = h_local % plan.size("sequence") == 0
    if cfg.attention == "ulysses":
        if not divides:
            raise ValueError(
                "ulysses attention needs local heads (%d) divisible by "
                "the sequence axis (%d); use attention='ring'"
                % (h_local, plan.size("sequence")))
        return "ulysses"
    if cfg.attention == "auto" and divides:
        return "ulysses"
    return "ring"


# one transformer block's parameter kinds, in declaration order — the
# per-layer (``l{i}_``) and stage-stacked (``blk_``) layouts both
# follow it, which is what keeps init_params' RNG draw order identical
# across plans (the bitwise same-seed contract)
_LAYER_KINDS = ("ln1_scale", "ln1_bias", "wq", "wk", "wv", "wo",
                "ln2_scale", "ln2_bias", "w1", "b1", "w2", "b2")

# parameters outside the block stack — pipe-replicated under
# ``pipeline=K``: only stage 0 (embeddings) / stage K-1 (final norm +
# head) produce nonzero gradients for them, completed by ONE psum over
# ``pipe`` (parallel/pipeline.py, reduce_replicated_grads)
_PIPE_REPLICATED = frozenset(
    ("embed", "pos_embed", "lnf_scale", "lnf_bias", "w_out"))


class MeshProgram:
    """One (config, plan) pair's concrete sharded program: parameter
    names/specs/local shapes, the deterministic global initializer, and
    the per-replica loss function (module docstring).

    With ``pipeline=K`` in the plan, the per-layer ``l{i}_*`` parameters
    are instead declared ONCE as stacked ``blk_*`` arrays with a leading
    ``(n_layers,)`` dim sharded over ``pipe`` — stage partitioning
    expressed through the exact same ``NamedSharding`` machinery as
    every other axis, so checkpoints/ZeRO/bf16 compose untouched — and
    :meth:`loss_replica` routes through the 1F1B schedule of
    ``parallel/pipeline.py`` (``M = cfg.microbatches`` or the stage
    count)."""

    def __init__(self, cfg, plan):
        from jax.sharding import PartitionSpec as P
        self.cfg = cfg
        self.plan = plan
        km, ks = plan.size("model"), plan.size("sequence")
        kp = plan.size("pipe")
        if cfg.n_heads % km:
            raise ValueError("n_heads %d must divide by the model axis %d"
                             % (cfg.n_heads, km))
        if cfg.d_ff % km:
            raise ValueError("d_ff %d must divide by the model axis %d"
                             % (cfg.d_ff, km))
        if cfg.vocab_size % km:
            raise ValueError("vocab_size %d must divide by the model "
                             "axis %d" % (cfg.vocab_size, km))
        if cfg.seq_len % max(ks, 1):
            raise ValueError("seq_len %d must divide by the sequence "
                             "axis %d" % (cfg.seq_len, ks))
        if cfg.n_layers % kp:
            raise ValueError("n_layers %d must divide by the pipeline "
                             "axis %d" % (cfg.n_layers, kp))
        self.attention_mode = _attention_mode(cfg, plan)
        self.pipelined = plan.present("pipe")
        self.n_micro = ((cfg.microbatches or kp)
                        if self.pipelined else None)
        self.pipe_replicated = (_PIPE_REPLICATED if self.pipelined
                                else frozenset())
        model = "model" if plan.present("model") else None
        d, h, e, f, v = (cfg.d_model, cfg.n_heads, cfg.head_dim,
                         cfg.d_ff, cfg.vocab_size)
        # kind -> (per-layer global shape, per-layer PartitionSpec
        # entries); axis names already collapsed (size-1 -> None)
        layer = [
            ("ln1_scale", (d,), (None,)),
            ("ln1_bias", (d,), (None,)),
            ("wq", (d, h, e), (None, model, None)),
            ("wk", (d, h, e), (None, model, None)),
            ("wv", (d, h, e), (None, model, None)),
            ("wo", (h, e, d), (model, None, None)),
            ("ln2_scale", (d,), (None,)),
            ("ln2_bias", (d,), (None,)),
            ("w1", (d, f), (None, model)),
            ("b1", (f,), (model,)),
            ("w2", (f, d), (model, None)),
            ("b2", (d,), (None,)),
        ]
        assert tuple(k for k, _, _ in layer) == _LAYER_KINDS
        # name -> (global shape, PartitionSpec) in parameter order
        specs = [("embed", (v, d), P(model, None)),
                 ("pos_embed", (cfg.seq_len, d), P())]
        if self.pipelined:
            specs += [("blk_" + kind, (cfg.n_layers,) + shape,
                       P("pipe", *entries))
                      for kind, shape, entries in layer]
        else:
            for i in range(cfg.n_layers):
                specs += [("l%d_%s" % (i, kind), shape, P(*entries))
                          for kind, shape, entries in layer]
        specs += [("lnf_scale", (d,), P()),
                  ("lnf_bias", (d,), P()),
                  ("w_out", (d, v), P(None, model))]
        self.param_names = [n for n, _, _ in specs]
        self._shapes = {n: s for n, s, _ in specs}
        self._specs = {n: p for n, _, p in specs}

    # -- layout -----------------------------------------------------------
    def partition_spec(self, name):
        return self._specs[name]

    def global_shape(self, name):
        return self._shapes[name]

    def local_shape(self, name):
        """The per-replica shard shape — what the ``axis_env`` trace and
        the ``shard_map`` body see."""
        spec = self._specs[name]
        shape = list(self._shapes[name])
        for dim, entry in enumerate(spec):
            if entry is not None:
                shape[dim] //= self.plan.size(entry)
        return tuple(shape)

    def local_batch_shape(self, global_batch):
        b = global_batch // self.plan.size("data")
        t = self.cfg.seq_len // self.plan.size("sequence")
        return (b, t)

    # -- init -------------------------------------------------------------
    @staticmethod
    def _init_leaf(rng, cfg, name, shape):
        """One per-layer-or-global leaf, by naming rule: scaled-normal
        weights, ones/zeros norms, zero biases.  ``shape`` is the
        PER-LAYER shape even in the stacked layout, so the RNG draws
        are identical across plans."""
        if name.endswith("_scale"):
            return _np.ones(shape, _np.float32)
        if name.endswith(("_bias", "b1", "b2")):
            return _np.zeros(shape, _np.float32)
        if name in ("embed", "pos_embed"):
            return (rng.randn(*shape) * cfg.init_scale
                    ).astype(_np.float32)
        # fan-in scaled: the contraction size of each matmul —
        # wo contracts (heads, head_dim), everything else dim 0
        fan_in = shape[0] * shape[1] if name.endswith("wo") \
            else shape[0]
        return (rng.randn(*shape) / _np.sqrt(max(fan_in, 1))
                ).astype(_np.float32)

    def init_params(self, seed=None):
        """Deterministic GLOBAL parameter arrays, name -> float32
        ndarray.  Same seed => bitwise-identical params at ANY plan (the
        numerics tests' baseline contract): the stacked ``blk_*`` layout
        draws each layer's leaves in the exact per-layer order of the
        replicated layout, then stacks — ``blk_wq[i]`` is bitwise
        ``l{i}_wq``."""
        cfg = self.cfg
        rng = _np.random.RandomState(
            cfg.init_seed if seed is None else int(seed))
        out = {}
        for name in ("embed", "pos_embed"):
            out[name] = self._init_leaf(rng, cfg, name, self._shapes[name])
        if self.pipelined:
            drawn = [{kind: self._init_leaf(
                rng, cfg, kind, self._shapes["blk_" + kind][1:])
                for kind in _LAYER_KINDS} for _ in range(cfg.n_layers)]
            for kind in _LAYER_KINDS:
                out["blk_" + kind] = _np.stack(
                    [drawn[i][kind] for i in range(cfg.n_layers)])
        else:
            for i in range(cfg.n_layers):
                for kind in _LAYER_KINDS:
                    name = "l%d_%s" % (i, kind)
                    out[name] = self._init_leaf(rng, cfg, kind,
                                                self._shapes[name])
        for name in ("lnf_scale", "lnf_bias", "w_out"):
            out[name] = self._init_leaf(rng, cfg, name, self._shapes[name])
        return out

    # -- the per-replica forward + loss ------------------------------------
    def _attend(self, q, k, v):
        from ..parallel.ring_attention import (local_attention,
                                               ring_attention,
                                               ulysses_attention)
        if self.attention_mode == "ring":
            return ring_attention(q, k, v, "sequence", causal=True)
        if self.attention_mode == "ulysses":
            return ulysses_attention(q, k, v, "sequence", causal=True)
        return local_attention(q, k, v, causal=True)

    def _embed_in(self, p, x):
        """Token + position embedding of a LOCAL ``(b, t)`` chunk onto
        the residual stream — the pipeline's stage-0 ingest."""
        from jax import lax

        from . import layers as L

        plan, t_local = self.plan, x.shape[1]
        h = L.vocab_parallel_embedding(p["embed"], x, plan)
        start = L.sequence_offset(plan, t_local)
        pos = lax.dynamic_slice(
            p["pos_embed"], (start, 0), (t_local, self.cfg.d_model))
        return h + pos[None].astype(h.dtype)

    def _block(self, lp, h):
        """One transformer block over per-layer param leaves ``lp``
        (kind -> local shard) — the same spelling whether the leaves
        come from ``l{i}_*`` names or a ``blk_*[j]`` stack slice."""
        import jax.numpy as jnp

        from . import layers as L

        plan = self.plan
        a = L.layer_norm(h, lp["ln1_scale"], lp["ln1_bias"])
        # Megatron f-op: every replicated activation entering a
        # column-parallel region needs its cotangent psum'd back
        a = L.copy_to_model(a, plan)
        q = jnp.einsum("btd,dhe->bthe", a, lp["wq"])
        k = jnp.einsum("btd,dhe->bthe", a, lp["wk"])
        v = jnp.einsum("btd,dhe->bthe", a, lp["wv"])
        o = self._attend(q, k, v)
        o = jnp.einsum("bthe,hed->btd", o, lp["wo"])
        h = h + L.row_parallel_out(o, plan)
        m = L.layer_norm(h, lp["ln2_scale"], lp["ln2_bias"])
        m = L.copy_to_model(m, plan)
        f = L.column_parallel_dense(m, lp["w1"], lp["b1"])
        f = jax.nn.gelu(f)
        f = f @ lp["w2"]
        return h + L.row_parallel_out(f, plan, bias=lp["b2"])

    def _head_loss(self, p, h, y):
        """Final norm + vocab-parallel head + mean token loss — the
        pipeline's last-stage scorer."""
        from . import layers as L

        plan = self.plan
        hf = L.layer_norm(h, p["lnf_scale"], p["lnf_bias"])
        hf = L.copy_to_model(hf, plan)
        logits = hf @ p["w_out"]
        return L.vocab_parallel_cross_entropy(logits, y, plan).mean()

    def loss_replica(self, train_vals, x, y, key):
        """Mean causal-LM loss of the LOCAL token chunk.  ``train_vals``
        follow ``param_names`` order (local shards); ``x``/``y`` are the
        local ``(B/Kd, T/Ks)`` int32 token/label chunks (labels already
        globally shifted by the feeder).  Collectives inside: the
        ``model``-axis psums of the sharded layers, the ``sequence``
        ring/all-to-all of attention and — under ``pipeline=K`` — the
        per-tick activation ``ppermute`` of the 1F1B schedule; NO
        data/sequence gradient reduction (the step wrapper owns that,
        exactly once: DST006)."""
        cfg, plan = self.cfg, self.plan
        p = dict(zip(self.param_names, train_vals))
        if self.pipelined:
            from ..parallel.pipeline import pipeline_loss

            layers_local = cfg.n_layers // plan.size("pipe")

            def stage_fn(h):
                for j in range(layers_local):
                    h = self._block(
                        {kind: p["blk_" + kind][j]
                         for kind in _LAYER_KINDS}, h)
                return h

            return pipeline_loss(
                lambda x_mb: self._embed_in(p, x_mb), stage_fn,
                lambda h, y_mb: self._head_loss(p, h, y_mb),
                x, y, plan, self.n_micro, act_dtype=p["embed"].dtype)
        h = self._embed_in(p, x)
        for i in range(cfg.n_layers):
            h = self._block({kind: p["l%d_%s" % (i, kind)]
                             for kind in _LAYER_KINDS}, h)
        return self._head_loss(p, h, y)

    def describe(self):
        out = {"config": self.cfg.describe(),
               "plan": self.plan.describe(),
               "attention_mode": self.attention_mode,
               "n_params": len(self.param_names)}
        if self.pipelined:
            out["pipeline"] = {"stages": self.plan.size("pipe"),
                               "microbatches": self.n_micro}
        return out
