"""Transformer LM over a 2-3D ``data × model × sequence`` mesh.

The model is spelled ONCE, per replica (the ``parallel/zero.py``
discipline): :meth:`MeshProgram.loss_replica` is a pure jax function
over LOCAL parameter shards and a LOCAL ``(B/Kd, T/Ks)`` token chunk,
with every cross-replica collective explicit.  The same function is

- jitted under ``shard_map`` by ``DataParallelTrainer(mesh_plan=...)``
  (the runtime), and
- traced with ``jax.make_jaxpr(axis_env=plan.axis_env())`` by
  ``trainer.mesh_report()`` and the ``tp_transformer_train_step``
  budget model (the hardware-free analysis),

so the executed program and the proven program can never drift.

Layer sharding (docs/transformer.md has the full table): token/output
embeddings vocab-parallel over ``model``; QKV column-parallel (heads
over ``model``); attention over the ``sequence`` axis via ring attention
(``parallel/ring_attention.py``) or Ulysses all-to-all when the local
head count divides; attention-out and MLP-down row-parallel with their
completing psum (the ``TP_ROW_PSUM`` seam); LayerNorms replicated.
Positions are global: each sequence rank offsets by
``axis_index("sequence") * T_local``.
"""
from __future__ import annotations

import numpy as _np

import jax

__all__ = ["TransformerLMConfig", "TransformerLM", "MeshProgram"]


class TransformerLMConfig:
    """Pinned-geometry transformer-LM hyperparameters.

    ``attention`` picks the sequence-parallel kernel: ``"ring"`` (K/V
    chunks rotate over ``ppermute`` — any head count, O(T/K) memory),
    ``"ulysses"`` (two all-to-alls swap sequence for head sharding —
    needs ``(n_heads / model) % sequence == 0``) or ``"auto"`` (Ulysses
    when the head count divides, else ring — the decision rule in
    docs/transformer.md).  With a collapsed sequence axis all three are
    plain local causal attention.
    """

    def __init__(self, vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                 d_ff=64, seq_len=64, attention="ring", init_seed=0,
                 init_scale=0.02):
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.d_ff = int(d_ff)
        self.seq_len = int(seq_len)
        self.attention = str(attention)
        self.init_seed = int(init_seed)
        self.init_scale = float(init_scale)
        if self.d_model % self.n_heads:
            raise ValueError("d_model %d must divide into n_heads %d"
                             % (self.d_model, self.n_heads))
        if self.attention not in ("ring", "ulysses", "auto"):
            raise ValueError("attention must be ring/ulysses/auto, got %r"
                             % (attention,))

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    def describe(self):
        return {k: getattr(self, k) for k in
                ("vocab_size", "d_model", "n_heads", "n_layers", "d_ff",
                 "seq_len", "attention", "init_seed")}


class TransformerLM:
    """The block handed to ``DataParallelTrainer(mesh_plan=...)`` — a
    thin config carrier implementing the mesh-program protocol the
    trainer's multi-axis tier consumes (``mesh_program(plan)``)."""

    def __init__(self, cfg):
        if not isinstance(cfg, TransformerLMConfig):
            cfg = TransformerLMConfig(**cfg)
        self.cfg = cfg

    def mesh_program(self, plan):
        return MeshProgram(self.cfg, plan)


def _attention_mode(cfg, plan):
    """The ring-vs-Ulysses decision rule (docs/transformer.md): Ulysses
    needs the LOCAL head count (heads already sharded over ``model``) to
    divide by the sequence-axis size; ``auto`` prefers it when legal
    (two all-to-alls move ~3x fewer bytes than a K-hop ring at moderate
    sequence lengths), ring otherwise."""
    if not plan.present("sequence"):
        return "local"
    h_local = cfg.n_heads // plan.size("model")
    divides = h_local % plan.size("sequence") == 0
    if cfg.attention == "ulysses":
        if not divides:
            raise ValueError(
                "ulysses attention needs local heads (%d) divisible by "
                "the sequence axis (%d); use attention='ring'"
                % (h_local, plan.size("sequence")))
        return "ulysses"
    if cfg.attention == "auto" and divides:
        return "ulysses"
    return "ring"


class MeshProgram:
    """One (config, plan) pair's concrete sharded program: parameter
    names/specs/local shapes, the deterministic global initializer, and
    the per-replica loss function (module docstring)."""

    def __init__(self, cfg, plan):
        from jax.sharding import PartitionSpec as P
        self.cfg = cfg
        self.plan = plan
        km, ks = plan.size("model"), plan.size("sequence")
        if cfg.n_heads % km:
            raise ValueError("n_heads %d must divide by the model axis %d"
                             % (cfg.n_heads, km))
        if cfg.d_ff % km:
            raise ValueError("d_ff %d must divide by the model axis %d"
                             % (cfg.d_ff, km))
        if cfg.vocab_size % km:
            raise ValueError("vocab_size %d must divide by the model "
                             "axis %d" % (cfg.vocab_size, km))
        if cfg.seq_len % max(ks, 1):
            raise ValueError("seq_len %d must divide by the sequence "
                             "axis %d" % (cfg.seq_len, ks))
        self.attention_mode = _attention_mode(cfg, plan)
        model = "model" if plan.present("model") else None
        d, h, e, f, v = (cfg.d_model, cfg.n_heads, cfg.head_dim,
                         cfg.d_ff, cfg.vocab_size)
        # name -> (global shape, PartitionSpec) in parameter order; the
        # spec's axis names are already collapsed (size-1 -> None)
        specs = [("embed", (v, d), P(model, None)),
                 ("pos_embed", (cfg.seq_len, d), P())]
        for i in range(cfg.n_layers):
            pre = "l%d_" % i
            specs += [
                (pre + "ln1_scale", (d,), P()),
                (pre + "ln1_bias", (d,), P()),
                (pre + "wq", (d, h, e), P(None, model, None)),
                (pre + "wk", (d, h, e), P(None, model, None)),
                (pre + "wv", (d, h, e), P(None, model, None)),
                (pre + "wo", (h, e, d), P(model, None, None)),
                (pre + "ln2_scale", (d,), P()),
                (pre + "ln2_bias", (d,), P()),
                (pre + "w1", (d, f), P(None, model)),
                (pre + "b1", (f,), P(model)),
                (pre + "w2", (f, d), P(model, None)),
                (pre + "b2", (d,), P()),
            ]
        specs += [("lnf_scale", (d,), P()),
                  ("lnf_bias", (d,), P()),
                  ("w_out", (d, v), P(None, model))]
        self.param_names = [n for n, _, _ in specs]
        self._shapes = {n: s for n, s, _ in specs}
        self._specs = {n: p for n, _, p in specs}

    # -- layout -----------------------------------------------------------
    def partition_spec(self, name):
        return self._specs[name]

    def global_shape(self, name):
        return self._shapes[name]

    def local_shape(self, name):
        """The per-replica shard shape — what the ``axis_env`` trace and
        the ``shard_map`` body see."""
        spec = self._specs[name]
        shape = list(self._shapes[name])
        for dim, entry in enumerate(spec):
            if entry is not None:
                shape[dim] //= self.plan.size(entry)
        return tuple(shape)

    def local_batch_shape(self, global_batch):
        b = global_batch // self.plan.size("data")
        t = self.cfg.seq_len // self.plan.size("sequence")
        return (b, t)

    # -- init -------------------------------------------------------------
    def init_params(self, seed=None):
        """Deterministic GLOBAL parameter arrays, name -> float32
        ndarray: scaled-normal weights, ones/zeros norms, zero biases.
        Same seed => bitwise-identical params at ANY plan (the numerics
        tests' baseline contract)."""
        cfg = self.cfg
        rng = _np.random.RandomState(
            cfg.init_seed if seed is None else int(seed))
        out = {}
        for name in self.param_names:
            shape = self._shapes[name]
            if name.endswith(("_scale", "lnf_scale")):
                out[name] = _np.ones(shape, _np.float32)
            elif name.endswith(("_bias", "b1", "b2")):
                out[name] = _np.zeros(shape, _np.float32)
            elif name in ("embed", "pos_embed"):
                out[name] = (rng.randn(*shape) * cfg.init_scale
                             ).astype(_np.float32)
            else:
                # fan-in scaled: the contraction size of each matmul —
                # wo contracts (heads, head_dim), everything else dim 0
                fan_in = shape[0] * shape[1] if name.endswith("wo") \
                    else shape[0]
                out[name] = (rng.randn(*shape) / _np.sqrt(max(fan_in, 1))
                             ).astype(_np.float32)
        return out

    # -- the per-replica forward + loss ------------------------------------
    def _attend(self, q, k, v):
        from ..parallel.ring_attention import (local_attention,
                                               ring_attention,
                                               ulysses_attention)
        if self.attention_mode == "ring":
            return ring_attention(q, k, v, "sequence", causal=True)
        if self.attention_mode == "ulysses":
            return ulysses_attention(q, k, v, "sequence", causal=True)
        return local_attention(q, k, v, causal=True)

    def loss_replica(self, train_vals, x, y, key):
        """Mean causal-LM loss of the LOCAL token chunk.  ``train_vals``
        follow ``param_names`` order (local shards); ``x``/``y`` are the
        local ``(B/Kd, T/Ks)`` int32 token/label chunks (labels already
        globally shifted by the feeder).  Collectives inside: the
        ``model``-axis psums of the sharded layers and the ``sequence``
        ring/all-to-all of attention — NO data/sequence gradient
        reduction (the step wrapper owns that, exactly once: DST006)."""
        import jax.numpy as jnp
        from jax import lax

        from . import layers as L

        cfg, plan = self.cfg, self.plan
        p = dict(zip(self.param_names, train_vals))
        t_local = x.shape[1]
        h = L.vocab_parallel_embedding(p["embed"], x, plan)
        start = L.sequence_offset(plan, t_local)
        pos = lax.dynamic_slice(
            p["pos_embed"], (start, 0), (t_local, cfg.d_model))
        h = h + pos[None]
        for i in range(cfg.n_layers):
            pre = "l%d_" % i
            a = L.layer_norm(h, p[pre + "ln1_scale"], p[pre + "ln1_bias"])
            # Megatron f-op: every replicated activation entering a
            # column-parallel region needs its cotangent psum'd back
            a = L.copy_to_model(a, plan)
            q = jnp.einsum("btd,dhe->bthe", a, p[pre + "wq"])
            k = jnp.einsum("btd,dhe->bthe", a, p[pre + "wk"])
            v = jnp.einsum("btd,dhe->bthe", a, p[pre + "wv"])
            o = self._attend(q, k, v)
            o = jnp.einsum("bthe,hed->btd", o, p[pre + "wo"])
            h = h + L.row_parallel_out(o, plan)
            m = L.layer_norm(h, p[pre + "ln2_scale"], p[pre + "ln2_bias"])
            m = L.copy_to_model(m, plan)
            f = L.column_parallel_dense(m, p[pre + "w1"], p[pre + "b1"])
            f = jax.nn.gelu(f)
            f = f @ p[pre + "w2"]
            h = h + L.row_parallel_out(f, plan, bias=p[pre + "b2"])
        hf = L.layer_norm(h, p["lnf_scale"], p["lnf_bias"])
        hf = L.copy_to_model(hf, plan)
        logits = hf @ p["w_out"]
        tok_loss = L.vocab_parallel_cross_entropy(logits, y, plan)
        return tok_loss.mean()

    def describe(self):
        return {"config": self.cfg.describe(),
                "plan": self.plan.describe(),
                "attention_mode": self.attention_mode,
                "n_params": len(self.param_names)}
