"""The multi-axis train step, spelled ONCE per replica.

``build_parts`` produces the two halves of the
``data × model × sequence × pipe`` training step over any
:class:`~mxnet_tpu.transformer.model.MeshProgram`:

- ``grads_part``: forward + backward on the local (batch, token) chunk
  — the model/sequence collectives live inside the program's
  ``loss_replica`` — then the step's ONE gradient exchange: every
  parameter gradient is ``pmean``'d over the plan's **batch axes**
  (``data`` and ``sequence``; model-sharded params keep their per-shard
  gradients — reducing them over ``model`` would mix unrelated shard
  coordinates, DST006; under ``pipeline=K`` only the pipe-replicated
  params are additionally psum-completed over ``pipe``, never the
  stage-local stacks — DST012), and under ``zero=1`` the flat LOCAL
  gradient is
  additionally reduce-scattered over ``data`` (arxiv 2004.13336 composed
  multiplicatively with the tensor/sequence sharding).
- ``update_part``: the optimizer applied shard-locally through a
  caller-supplied ``apply_update`` (the trainer passes the real gluon
  ``Optimizer.update`` via ``functional_optimizer_update``; the budget
  fixture passes an inline SGD+momentum), all-gathering the flat params
  back over ``data`` under ``zero=1`` (the DST007 pair).

Used two ways so runtime and analysis can never drift (the
``parallel/zero.py`` discipline): ``build_runtime_fns`` wraps the parts
in two jitted ``shard_map`` programs over the plan's mesh;
``build_replica_step`` composes them for
``jax.make_jaxpr(axis_env=plan.axis_env())`` — the
``tp_transformer_train_step`` budget tape and ``trainer.mesh_report()``.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["TPZeroPlan", "build_parts", "build_replica_step",
           "build_runtime_fns", "sgd_momentum_update"]


class TPZeroPlan:
    """ZeRO-1 flat layout over the LOCAL parameter space of one model
    rank: local shards raveled f32 in ``param_names`` order, padded to
    the data-axis size K.  Because model-sharded params are replicated
    over ``data``, sharding their optimizer state over ``data`` is
    exactly the ZeRO-1 story, per model rank — the two shardings
    compose multiplicatively."""

    def __init__(self, program, k_data):
        self.k = int(k_data)
        self.names = list(program.param_names)
        self.local_shapes = [program.local_shape(n) for n in self.names]
        self.sizes = [int(_np.prod(s)) if s else 1
                      for s in self.local_shapes]
        self.total = int(sum(self.sizes))
        self.padded = -(-self.total // self.k) * self.k
        self.shard = self.padded // self.k

    def describe(self):
        return {"k": self.k, "total": self.total, "padded": self.padded,
                "shard": self.shard}


def sgd_momentum_update(momentum=0.9):
    """The budget fixture's inline elementwise optimizer:
    ``apply_update(i, w, g, state_leaves, lr, t) -> (new_w, new_leaves)``
    with one momentum leaf per parameter — numerically the gluon
    ``sgd`` rule the runtime trainer applies, spelled without the
    optimizer registry so the fixture stays dependency-light."""
    mu = float(momentum)

    def apply_update(_i, w, g, state_leaves, lr, _t):
        (m,) = state_leaves
        new_m = mu * m + g
        return w - lr * new_m, (new_m,)

    return apply_update


def _flatten_pad(vals, plan, jnp):
    parts = [v.ravel().astype(jnp.float32) for v in vals]
    pad = plan.padded - plan.total
    if pad:
        parts.append(jnp.zeros((pad,), jnp.float32))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _unflatten(flat, plan):
    out, off = [], 0
    for shape, size in zip(plan.local_shapes, plan.sizes):
        out.append(flat[off:off + size].reshape(shape))
        off += size
    return tuple(out)


def build_parts(program, apply_update, state_leaf_counts, zero=0,
                zero_plan=None, compute_dtype=None):
    """``(grads_part, update_part)`` over LOCAL shards (the ``shard_map``
    / ``axis_env`` view).  ``state_leaf_counts[i]`` is parameter ``i``'s
    optimizer-state leaf count (flat leaves concatenated across params in
    order); under ``zero=1`` every leaf is instead one flat
    ``(shard,)``-sized slice of the :class:`TPZeroPlan` space.

    ``compute_dtype`` (mixed precision, docs/precision.md): the mesh
    tier keeps its params f32 — they ARE the masters — and casts params
    + batch to the compute dtype at the loss boundary, so activations
    run bf16 while gradients come back f32 through the cast transpose
    and every collective reduces f32 (the tightened DST004 contract).
    No loss scaling here: bf16 carries f32's 8-bit exponent, so grads
    cannot flush to zero the way f16's 5-bit exponent loses them."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    plan = program.plan
    batch_axes = plan.batch_axes()
    if zero and zero_plan is None:
        raise ValueError("zero=1 needs a TPZeroPlan")
    reduced = (compute_dtype is not None
               and jnp.dtype(compute_dtype) != jnp.float32)

    def _to_compute(v):
        if reduced and hasattr(v, "dtype") \
                and jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(compute_dtype)
        return v

    def grads_part(train_vals, x, y, key):
        if reduced:
            x_c = _to_compute(x)

            def loss_of(tv):
                return program.loss_replica(
                    tuple(_to_compute(w) for w in tv), x_c, y, key)

            loss, grads = jax.value_and_grad(loss_of)(tuple(train_vals))
            loss = loss.astype(jnp.float32)
            # f32 already via the cast transpose — spelled out so the
            # wire contract survives a program whose loss math changes
            grads = tuple(g.astype(jnp.float32) for g in grads)
        else:
            loss, grads = jax.value_and_grad(program.loss_replica)(
                tuple(train_vals), x, y, key)
        if plan.present("pipe"):
            # the ONE pipe-axis exchange: complete the pipe-replicated
            # params' partial grads; stage-local blk_* grads pass
            # through (reducing them over pipe mixes layers — DST012)
            from ..parallel.pipeline import reduce_replicated_grads
            grads = reduce_replicated_grads(
                grads, program.param_names, program.pipe_replicated)
        if batch_axes:
            loss = lax.pmean(loss, batch_axes)
        if zero:
            # sequence ranks hold partial grads of the same shard: mean
            # them first, then scatter the data axis so each data rank
            # lands exactly its owned slice of the flat local space
            if plan.present("sequence"):
                grads = tuple(lax.pmean(g, "sequence") for g in grads)
            flat_g = _flatten_pad(grads, zero_plan, jnp)
            if plan.present("data"):
                g_out = lax.psum_scatter(
                    flat_g, "data", scatter_dimension=0,
                    tiled=True) / zero_plan.k
            else:
                g_out = flat_g
            return g_out, loss
        if batch_axes:
            grads = tuple(lax.pmean(g, batch_axes) for g in grads)
        return tuple(grads), loss

    def update_part(train_vals, state_leaves, grads, lr, t):
        if zero:
            flat_w = _flatten_pad(train_vals, zero_plan, jnp)
            if plan.present("data"):
                idx = lax.axis_index("data")
                w_sh = lax.dynamic_slice(
                    flat_w, (idx * zero_plan.shard,), (zero_plan.shard,))
            else:
                w_sh = flat_w
            new_w_sh, new_leaves = apply_update(
                0, w_sh, grads, tuple(state_leaves), lr, t)
            if plan.present("data"):
                new_flat = lax.all_gather(new_w_sh, "data", tiled=True)
            else:
                new_flat = new_w_sh
            return _unflatten(new_flat, zero_plan), tuple(new_leaves)
        new_vals, new_leaves, off = [], [], 0
        for i, (w, g) in enumerate(zip(train_vals, grads)):
            n = state_leaf_counts[i]
            leaves = tuple(state_leaves[off:off + n])
            off += n
            nw, nl = apply_update(i, w, g, leaves, lr, t)
            new_vals.append(nw)
            new_leaves.extend(nl)
        return tuple(new_vals), tuple(new_leaves)

    return grads_part, update_part


def build_replica_step(program, apply_update, state_leaf_counts, zero=0,
                       zero_plan=None, compute_dtype=None):
    """Both halves composed into one per-replica function — the analysis
    spelling.  ``step(train_vals, state_leaves, x, y, key, lr, t) ->
    (loss, new_vals, new_state_leaves)``; trace with
    ``jax.make_jaxpr(axis_env=program.plan.axis_env())``."""
    grads_part, update_part = build_parts(
        program, apply_update, state_leaf_counts, zero=zero,
        zero_plan=zero_plan, compute_dtype=compute_dtype)

    def replica_step(train_vals, state_leaves, x, y, key, lr, t):
        grads, loss = grads_part(train_vals, x, y, key)
        new_vals, new_leaves = update_part(train_vals, state_leaves,
                                           grads, lr, t)
        return loss, new_vals, new_leaves

    return replica_step


def build_runtime_fns(program, apply_update, state_leaf_counts, mesh,
                      state_specs, zero=0, zero_plan=None,
                      compute_dtype=None):
    """``(grad_fn, update_fn)`` — the jitted ``shard_map`` programs the
    trainer dispatches each step.  Params ride their
    ``program.partition_spec``; the batch rides ``plan.batch_spec()``;
    optimizer-state leaves ride ``state_specs`` (per-param specs, or the
    flat ``P(("model", "data"))`` space under ``zero=1``).  ``update_fn``
    donates params, states and gradients so the update happens in place
    in HBM."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring_attention import _shard_map

    plan = program.plan
    grads_part, update_part = build_parts(
        program, apply_update, state_leaf_counts, zero=zero,
        zero_plan=zero_plan, compute_dtype=compute_dtype)
    param_specs = tuple(program.partition_spec(n)
                        for n in program.param_names)
    batch_spec = plan.batch_spec()
    if zero:
        flat_axes = tuple(a for a in ("pipe", "model", "data")
                          if plan.present(a))
        grad_out = P(flat_axes) if flat_axes else P()
    else:
        grad_out = param_specs
    grad_fn = jax.jit(_shard_map(
        grads_part, mesh,
        in_specs=(param_specs, batch_spec, batch_spec, P()),
        out_specs=(grad_out, P())))
    update_fn = jax.jit(_shard_map(
        update_part, mesh,
        in_specs=(param_specs, tuple(state_specs), grad_out, P(), P()),
        out_specs=(param_specs, tuple(state_specs))),
        donate_argnums=(0, 1, 2))
    return grad_fn, update_fn
