"""Host-only pipeline-tier bench (the r05 subprocess pattern).

Run as ``python -m mxnet_tpu.transformer.pp_bench`` under
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
(bench.py's ``pipeline`` stage does, BEFORE backend acquisition, so the
keys stay live when the TPU is down).  Emits one JSON line:

- ``pp_modeled_bubble_frac``: the pinned ``pp_transformer_train_step``
  fixture's modeled 1F1B bubble fraction ``(K-1)/(K-1+M)``
  (deterministic — gated lower_rel in tools/bench_compare.py: a grown
  bubble means the schedule geometry regressed);
- ``pp_modeled_pipe_axis_bytes``: the fixture's pipe-axis wire bytes
  per step (deterministic — growing stage-boundary traffic is the
  regression);
- ``pp_tokens_per_sec_host``: real tokens/sec of a
  ``pipe=2 x model=2 x data=2`` train loop on the virtual host mesh
  (throughput gate);
- ``pp_numerics_ok``: 1.0 iff the pipelined run's losses match the
  replicated single-axis baseline to tolerance over several steps —
  the end-to-end 1F1B numerics contract, gated at zero slack.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

TOL = 2e-3          # loss match tolerance vs the replicated baseline
TRAIN_STEPS = 10
MEASURE_FROM = 4    # skip compile steps in the throughput window


def _corpus(vocab, length, seed=7):
    rng = np.random.RandomState(seed)
    succ = rng.permutation(vocab)
    out = np.empty(length, np.int32)
    tok = 0
    for i in range(length):
        out[i] = tok
        tok = int(succ[tok]) if rng.rand() < 0.8 \
            else int(rng.randint(vocab))
    return out


def _run(plan, cfg_kw, batch, steps, seed=0):
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from ..ndarray import NDArray
    from ..parallel import DataParallelTrainer
    from .model import TransformerLM, TransformerLMConfig

    mx.random.seed(seed)
    cfg = TransformerLMConfig(**cfg_kw)
    trainer = DataParallelTrainer(
        TransformerLM(cfg), None, "sgd",
        {"learning_rate": 0.2, "momentum": 0.9}, mesh_plan=plan)
    corpus = _corpus(cfg.vocab_size, 2048, seed=seed + 7)
    rng = np.random.RandomState(seed + 11)
    hi = len(corpus) - cfg.seq_len - 1
    losses, times = [], []
    for step in range(steps):
        starts = rng.randint(0, hi, size=batch)
        x = np.stack([corpus[s:s + cfg.seq_len] for s in starts])
        y = np.stack([corpus[s + 1:s + cfg.seq_len + 1] for s in starts])
        t0 = time.perf_counter()
        loss = trainer.step(NDArray(jnp.asarray(x)),
                            NDArray(jnp.asarray(y)))
        losses.append(float(loss.asnumpy()))   # sync: per-step timing
        times.append(time.perf_counter() - t0)
    return losses, times


def main():
    from ..analysis.budget_models import build_model
    from ..parallel.mesh import MeshPlan

    out = {}

    # modeled (deterministic, device-free): the budget fixture's 1F1B
    # schedule geometry and pipe-axis wire traffic
    _, findings, shard = build_model("pp_transformer_train_step")
    out["pp_modeled_bubble_frac"] = round(
        float(shard.extras["pp_modeled_bubble_frac"]), 4)
    out["pp_modeled_pipe_axis_bytes"] = int(
        shard.extras["pp_modeled_pipe_axis_bytes"])
    out["pp_hop_bytes"] = int(shard.extras["pp_hop_bytes"])
    out["pp_budget_findings"] = len(findings)

    cfg_kw = dict(vocab_size=64, d_model=64, n_heads=4, n_layers=2,
                  d_ff=128, seq_len=128)
    batch = 8

    pp_losses, times = _run(
        MeshPlan(data=2, model=2, pipeline=2), cfg_kw, batch,
        TRAIN_STEPS)
    window = times[MEASURE_FROM:]
    tokens = batch * cfg_kw["seq_len"]
    out["pp_tokens_per_sec_host"] = round(
        tokens / (sum(window) / len(window)), 1)

    base_losses, _ = _run(MeshPlan(data=1), cfg_kw, batch, TRAIN_STEPS)
    err = max(abs(a - b) for a, b in zip(pp_losses, base_losses))
    out["pp_numerics_max_loss_err"] = round(err, 6)
    out["pp_numerics_ok"] = 1.0 if err <= TOL else 0.0

    print(json.dumps(out))
    return 0 if out["pp_numerics_ok"] and not out["pp_budget_findings"] \
        else 1


if __name__ == "__main__":
    sys.exit(main())
