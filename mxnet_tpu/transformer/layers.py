"""Per-replica tensor-parallel transformer layers (GSPMD spelled out).

Every function here is the **per-replica** view of one Megatron-style
sharded layer (arxiv 1810.09868's annotations, written as the explicit
``shard_map`` program the compiler would derive): inputs are LOCAL
shards, collectives are explicit ``lax`` calls over the plan's collapsed
axes, and a :class:`~mxnet_tpu.parallel.mesh.MeshPlan` with a size-1
``model`` axis produces **zero** model collectives — the replicated
spelling and the sharded spelling are the same code.

The sharding grammar (docs/transformer.md has the full table):

- **column-parallel** (out-feature dim over ``model``): no collective —
  the activation comes out model-sharded (QKV heads, MLP ``w1``).
- **row-parallel** (in-feature dim over ``model``): each rank's matmul
  produces a partial sum; :func:`row_parallel_out` completes it with the
  ``psum`` over ``model`` (attention output proj, MLP ``w2``).  This is
  the layer the whole proof hangs on — see the seam below.
- **vocab-parallel** (vocab dim over ``model``): the embedding gathers
  from the local vocab slice and psums the misses away; the logit/loss
  side never materializes the full vocab — max/sum-exp/picked-logit are
  completed by ``pmax``/``psum`` over ``model``
  (:func:`vocab_parallel_cross_entropy`, the "final-logit psum").

``TP_ROW_PSUM`` is the **mutation seam** (the ``parallel/zero.py``
``ZERO1_RUNTIME_ALL_GATHER`` discipline): flipping it False deletes the
row-parallel output psum — the classic "forgot the all-reduce" bug where
every rank trains on its own partial activations — and the
``tp_transformer_train_step`` budget gate must fail rc=2 with the
pending-partial-sum DST001 named per parameter
(tests/test_transformer.py, subprocess).  Production code never touches
it.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["TP_ROW_PSUM", "layer_norm", "column_parallel_dense",
           "row_parallel_out", "copy_to_model", "complete_psum",
           "vocab_parallel_embedding", "vocab_parallel_cross_entropy",
           "sequence_offset"]

# runtime+analysis mutation seam (see module docstring) — tests only
TP_ROW_PSUM = True


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _complete_psum(x, axis):
    return lax.psum(x, axis)


def _complete_psum_fwd(x, axis):
    return lax.psum(x, axis), None


def _complete_psum_bwd(axis, _res, g):
    # Megatron's ``g`` operator: the psum completes per-rank partials
    # into ONE replicated value consumed by ONE (replicated) downstream
    # loss, so each rank's partial receives exactly the replicated
    # cotangent.  jax's default psum transpose (psum again) would
    # instead differentiate Σ_ranks L_r and scale every upstream path
    # by the axis size per crossed psum.
    return (g,)


_complete_psum.defvjp(_complete_psum_fwd, _complete_psum_bwd)


def complete_psum(x, plan, axis="model"):
    """Sum per-rank partials over ``axis`` into the replicated value
    (identity backward — module docstring); collapses to identity when
    the axis is absent from the plan."""
    if plan.present(axis):
        return _complete_psum(x, axis)
    return x


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _model_region(x, axis):
    return x


def _model_region_fwd(x, axis):
    return x, None


def _model_region_bwd(axis, _res, g):
    return (lax.psum(g, axis),)


_model_region.defvjp(_model_region_fwd, _model_region_bwd)


def copy_to_model(x, plan):
    """Megatron's ``f`` operator: identity forward, ``psum`` over
    ``model`` backward.  A replicated activation entering a
    column-parallel region gets per-shard partial cotangents (each rank
    back-propagates only its feature/head slice); this completes them —
    without it the grads of every replicated parameter upstream (LNs,
    embeddings) silently diverge across model ranks after one step."""
    if plan.present("model"):
        return _model_region(x, "model")
    return x


def layer_norm(x, scale, bias, eps=1e-5):
    """LayerNorm over the (replicated) feature dim — no collectives.

    The layernorm→dense chain is a top-ranked mxfuse candidate
    (docs/fusion.md): when the fused kernel is enabled (TPU with a
    lane-aligned f32 feature dim, or ``MXTPU_FUSED_LAYERNORM=1``), the
    normalization runs as ONE Pallas pass over HBM instead of the
    mean/var/normalize eqn chain; numerics match this spelling to float
    tolerance (tests/test_fusion.py) and the backward recomputes
    statistics flash-style."""
    from ..ops import fused_optimizer as _fused
    if _fused.fused_layernorm_enabled(feature_dim=x.shape[-1],
                                      dtype=x.dtype):
        return _fused.fused_layer_norm(x, scale, bias, eps)
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * scale + bias


def column_parallel_dense(x, w_local, b_local=None):
    """``x @ W`` with W column-sharded over ``model``: the contraction
    dim is replicated, so there is no collective — the output's feature
    dim is the local shard (heads, MLP hidden)."""
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_out(partial, plan, bias=None):
    """Complete a row-parallel matmul's partial sum over ``model`` and
    add the (replicated) bias AFTER the reduction — the one collective
    of the attention output / MLP down projection, and the seam the
    budget gate kills (module docstring)."""
    if plan.present("model") and TP_ROW_PSUM:
        partial = _complete_psum(partial, "model")
    if bias is not None:
        partial = partial + bias
    return partial


def sequence_offset(plan, t_local):
    """Global position of this replica's first token: the sequence axis
    shards tokens in order, so chunk ``s`` starts at ``s * t_local``."""
    if plan.present("sequence"):
        return lax.axis_index("sequence") * t_local
    return 0


def vocab_parallel_embedding(table_local, ids, plan):
    """Gather rows of a vocab-sharded ``(V/Km, d)`` table for GLOBAL ids:
    out-of-shard ids gather row 0 and are masked to zero, then one psum
    over ``model`` fills every position from whichever rank owns it."""
    if not plan.present("model"):
        return jnp.take(table_local, ids, axis=0)
    v_local = table_local.shape[0]
    off = lax.axis_index("model") * v_local
    local = ids - off
    in_range = (local >= 0) & (local < v_local)
    emb = jnp.take(table_local, jnp.where(in_range, local, 0), axis=0)
    emb = emb * in_range[..., None].astype(emb.dtype)
    return _complete_psum(emb, "model")


def vocab_parallel_cross_entropy(logits_local, labels, plan):
    """Per-token causal-LM loss over vocab-sharded logits
    ``(..., V/Km)`` without ever materializing the full vocab row:
    the stable logsumexp's max rides ``pmax``, its sum-of-exponentials
    and the picked target logit ride ``psum`` — the "final-logit psum"
    trio over ``model``.  Labels are GLOBAL vocab ids."""
    # the logsumexp max is numerical stability only (its gradient
    # cancels exactly), so it is stopped — pmax has no VJP rule anyway
    m_local = lax.stop_gradient(logits_local.max(axis=-1))
    if plan.present("model"):
        v_local = logits_local.shape[-1]
        off = lax.axis_index("model") * v_local
        m = lax.pmax(m_local, "model")
        sumexp = jnp.exp(logits_local - m[..., None]).sum(axis=-1)
        sumexp = _complete_psum(sumexp, "model")
        local = labels - off
        in_range = (local >= 0) & (local < v_local)
        picked = jnp.take_along_axis(
            logits_local, jnp.where(in_range, local, 0)[..., None],
            axis=-1)[..., 0]
        picked = _complete_psum(picked * in_range.astype(picked.dtype),
                                "model")
    else:
        m = m_local
        sumexp = jnp.exp(logits_local - m[..., None]).sum(axis=-1)
        picked = jnp.take_along_axis(logits_local, labels[..., None],
                                     axis=-1)[..., 0]
    return jnp.log(sumexp) + m - picked
