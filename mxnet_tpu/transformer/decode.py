"""KV-cached autoregressive decode over the PR-14 MeshProgram.

The serve-side twin of :mod:`mxnet_tpu.transformer.model`: the SAME
parameter layout, initializer and per-layer math as
``MeshProgram.loss_replica``, refactored into the two phases an
autoregressive server actually runs —

- :meth:`DecodeProgram.prefill_replica`: one full causal forward over a
  length-bucketed prompt, writing every position's K/V into the paged
  cache and returning the last real position's next-token logits.
  Causality makes bucket padding *exact*: a padded key at position
  ``>= length`` is only visible to queries at positions ``>= length``,
  so real-position logits are bitwise independent of the bucket chosen
  (the padding-equivalence test in tests/test_decode.py).
- :meth:`DecodeProgram.decode_replica`: one token step for a fixed
  batch of sequence slots — embed the last token, write its K/V at
  ``page_table[b, length // page_size], length % page_size``, attend
  over the gathered per-sequence pages with a ``position <= length``
  mask, and emit full-vocab logits (the model-axis shards all-gathered;
  the vocab is tiny next to the cache).

**Paged cache layout** (docs/serving.md has the full picture): one pool
per model rank, ``(n_layers, n_pages, page_size, heads_local,
head_dim)`` for K and V each — a *page* holds ``page_size`` tokens of
K+V across ALL layers, so the host allocator hands out whole-sequence
page lists and admission control counts pages, not worst-case
sequences.  Page 0 is the reserved scratch page: idle batch slots carry
all-zero page tables and a sequence that overruns its allocation writes
(and reads) scratch — corruption of live sequences is impossible by
construction, the host side merely must not *trust* tokens past the
allocation (DecodeBatcher stops at ``max_new_tokens``).

Both phases are spelled ONCE (the ``parallel/zero.py`` discipline):
:meth:`build_runtime_fns` jits them (under ``shard_map`` when the plan
keeps a model axis), and the same bound methods feed
``jax.make_jaxpr(axis_env=plan.axis_env())`` in the ``decode_step``
budget model — the executed decode and the proven decode can never
drift.

``DECODE_WRITE_KV`` is the tier's **mutation seam** (the ``TP_ROW_PSUM``
discipline): flipping it False skips the cache write — the classic
stale-KV bug where every decode step attends over a cache missing its
own token — and the ``decode_step`` budget gate must fail rc=2 with the
cached-vs-full-forward mismatch named (tests/test_decode.py,
subprocess).  Production code never touches it.

**int8 KV-cache** (``kv_dtype="int8"``, docs/precision.md): the pools
hold int8 codes quantized per (layer, page, token, head) row — scale =
``amax(|kv_row|)/127`` over ``head_dim``, stored f32 in a scale pool of
the same page layout beside the codes — and the dequant
(``codes * scale``) is fused into the attention read, so a page costs
~1/4 the f32 bytes (codes) plus a ``head_dim``-th of scales:
``bytes_per_page()`` is dtype-aware and everything that counts pages
(SRV004 admission, the capacity simulator, ``tools/capacity.py``)
inherits the drop.  The write path quantizes the freshly-computed K/V
row in the same kernel pass as the cache scatter.
"""
from __future__ import annotations

import numpy as _np

import jax

from ..parallel.mesh import MeshPlan
from .model import MeshProgram, TransformerLMConfig

__all__ = ["DecodeProgram", "DECODE_WRITE_KV"]

# runtime+analysis mutation seam (module docstring) — tests only
DECODE_WRITE_KV = True

_NEG_INF = -1e30

_KV_DTYPES = {None: "float32", "f32": "float32", "float32": "float32",
              "int8": "int8"}


def _kv_quant(x, jnp):
    """Quantize one K/V row-block along ``head_dim``: symmetric
    per-row amax/127 scales (f32), int8 codes.  ``x`` is ``(...,
    head_dim)``; returns ``(codes int8, scales f32 (..., 1))``."""
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12).astype(jnp.float32)
    codes = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return codes, scale


def _kv_dequant(codes, scale, jnp):
    """The fused-into-attention read: ``codes * scale`` back to f32."""
    return codes.astype(jnp.float32) * scale


def _full_logits(logits_local, plan):
    """All-gather vocab-sharded ``(B, V/Km)`` logits over ``model`` into
    the replicated ``(B, V)`` row every rank can argmax.  The one place
    decode pays a vocab-sized collective — cheap by design (the vocab is
    tiny next to the KV pages) and absent when the axis collapses."""
    from jax import lax
    if plan.present("model"):
        return lax.all_gather(logits_local, "model", axis=1, tiled=True)
    return logits_local


class DecodeProgram:
    """One ``(config, plan)`` pair's concrete KV-cached decode program.

    ``plan`` may keep only the ``model`` axis: batch is a host concern
    (continuous batching joins/leaves slots per step) and the sequence
    dimension lives in the cache, so ``data``/``sequence`` must be
    collapsed.  ``page_size`` fixes the token-block granularity; the
    per-sequence page-table width is ``seq_len / page_size`` (a full
    sequence's worth of slots, unallocated tails pointing at scratch).
    """

    def __init__(self, cfg, plan=None, page_size=8, kv_dtype=None):
        if not isinstance(cfg, TransformerLMConfig):
            cfg = TransformerLMConfig(**cfg)
        plan = MeshPlan.coerce(plan) or MeshPlan(data=1)
        plan = plan.resolve(1) if plan.data is None else plan
        if plan.size("data") != 1 or plan.size("sequence") != 1:
            raise ValueError(
                "DecodeProgram serves over the model axis only (batch is "
                "the host's continuous-batching concern, sequence lives "
                "in the cache); got %r" % (plan,))
        if cfg.seq_len % int(page_size):
            raise ValueError(
                "page_size %d must divide seq_len %d"
                % (page_size, cfg.seq_len))
        key = kv_dtype if kv_dtype is None else str(kv_dtype)
        if key not in _KV_DTYPES:
            raise ValueError("kv_dtype must be one of %s, got %r"
                             % (sorted(k for k in _KV_DTYPES if k),
                                kv_dtype))
        self.cfg = cfg
        self.plan = plan
        self.program = MeshProgram(cfg, plan)
        self.page_size = int(page_size)
        self.pages_per_seq = cfg.seq_len // self.page_size
        self.heads_local = cfg.n_heads // plan.size("model")
        self.kv_dtype = _KV_DTYPES[key]
        self.kv_quantized = self.kv_dtype == "int8"

    # -- geometry ----------------------------------------------------------
    def cache_shape(self, n_pages):
        """LOCAL (per model rank) K or V pool shape."""
        return (self.cfg.n_layers, int(n_pages), self.page_size,
                self.heads_local, self.cfg.head_dim)

    def scale_shape(self, n_pages):
        """LOCAL per-row scale pool shape (int8 KV only): one f32 scale
        per (layer, page, token, head) row, trailing 1 so the dequant
        broadcasts straight over ``head_dim``."""
        return (self.cfg.n_layers, int(n_pages), self.page_size,
                self.heads_local, 1)

    def global_cache_shape(self, n_pages):
        return (self.cfg.n_layers, int(n_pages), self.page_size,
                self.cfg.n_heads, self.cfg.head_dim)

    def global_scale_shape(self, n_pages):
        return (self.cfg.n_layers, int(n_pages), self.page_size,
                self.cfg.n_heads, 1)

    def cache_np_dtype(self):
        """numpy dtype of the cache pools (the scale pools are always
        f32)."""
        return _np.int8 if self.kv_quantized else _np.float32

    def bytes_per_page(self):
        """GLOBAL bytes one page pins across all model ranks: K+V for
        ``page_size`` tokens through every layer — the unit the page
        allocator and pages-based fleet admission count in.  Dtype-
        aware: int8 pages carry 1-byte codes plus one f32 scale per
        (layer, token, head) row — well under half the f32 page."""
        cfg = self.cfg
        rows = 2 * cfg.n_layers * self.page_size * cfg.n_heads
        if self.kv_quantized:
            return rows * cfg.head_dim * 1 + rows * 4
        return rows * cfg.head_dim * 4

    def pages_for(self, n_tokens):
        """Pages a sequence of ``n_tokens`` total (prompt + generation
        budget) pins, capped nowhere — callers check against the pool."""
        return -(-int(n_tokens) // self.page_size)

    # -- the per-replica phases (spelled ONCE) ------------------------------
    def prefill_replica(self, train_vals, cache_k, cache_v, page_table,
                        tokens, lengths, scale_k=None, scale_v=None):
        """Full causal forward over a ``(B, Tb)`` padded prompt bucket:
        returns ``(logits, cache_k, cache_v)`` with the last *real*
        position's full-vocab next-token logits and every position's K/V
        scattered into ``page_table``'s pages (page-table tails of 0
        land in scratch — see the module docstring).  ``Tb`` must be a
        page multiple (the bucket ladder is built that way).  Under
        ``kv_dtype="int8"`` the per-row scale pools ride along and the
        return grows to ``(logits, cache_k, cache_v, scale_k,
        scale_v)``."""
        import jax.numpy as jnp

        from . import layers as L

        cfg, plan = self.cfg, self.plan
        p = dict(zip(self.program.param_names, train_vals))
        B, Tb = tokens.shape
        ps = self.page_size
        h = L.vocab_parallel_embedding(p["embed"], tokens, plan)
        h = h + p["pos_embed"][:Tb][None]
        ks, vs = [], []
        for i in range(cfg.n_layers):
            pre = "l%d_" % i
            a = L.layer_norm(h, p[pre + "ln1_scale"], p[pre + "ln1_bias"])
            a = L.copy_to_model(a, plan)
            q = jnp.einsum("btd,dhe->bthe", a, p[pre + "wq"])
            k = jnp.einsum("btd,dhe->bthe", a, p[pre + "wk"])
            v = jnp.einsum("btd,dhe->bthe", a, p[pre + "wv"])
            ks.append(k)
            vs.append(v)
            o = self._causal_attention(q, k, v)
            o = jnp.einsum("bthe,hed->btd", o, p[pre + "wo"])
            h = h + L.row_parallel_out(o, plan)
            m = L.layer_norm(h, p[pre + "ln2_scale"], p[pre + "ln2_bias"])
            m = L.copy_to_model(m, plan)
            f = L.column_parallel_dense(m, p[pre + "w1"], p[pre + "b1"])
            f = jax.nn.gelu(f)
            f = f @ p[pre + "w2"]
            h = h + L.row_parallel_out(f, plan, bias=p[pre + "b2"])
        # next-token logits of the last real position only: slice the
        # hidden state BEFORE the vocab projection so the bucket tail
        # never pays the matmul
        last = jnp.take_along_axis(
            h, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)
        hf = L.layer_norm(last, p["lnf_scale"], p["lnf_bias"])
        hf = L.copy_to_model(hf, plan)
        logits = _full_logits((hf @ p["w_out"])[:, 0], plan)
        # scatter the prompt K/V into pages: bucket position t lands at
        # (page_table[b, t // ps], t % ps); unallocated table tails are
        # 0 and land in scratch
        npg = Tb // ps
        pages = page_table[:, :npg]
        kp = jnp.stack(ks).reshape(
            cfg.n_layers, B, npg, ps, self.heads_local, cfg.head_dim)
        vp = jnp.stack(vs).reshape(
            cfg.n_layers, B, npg, ps, self.heads_local, cfg.head_dim)
        if self.kv_quantized:
            kp, ksc = _kv_quant(kp, jnp)
            vp, vsc = _kv_quant(vp, jnp)
            if DECODE_WRITE_KV:
                cache_k = cache_k.at[:, pages].set(kp)
                cache_v = cache_v.at[:, pages].set(vp)
                scale_k = scale_k.at[:, pages].set(ksc)
                scale_v = scale_v.at[:, pages].set(vsc)
            return logits, cache_k, cache_v, scale_k, scale_v
        if DECODE_WRITE_KV:
            cache_k = cache_k.at[:, pages].set(kp)
            cache_v = cache_v.at[:, pages].set(vp)
        return logits, cache_k, cache_v

    def decode_replica(self, train_vals, cache_k, cache_v, page_table,
                       lengths, tokens, scale_k=None, scale_v=None):
        """One token step for every batch slot: ``tokens (B,)`` are the
        slots' last tokens, ``lengths (B,)`` the cached token counts (=
        the new token's position).  Writes the new K/V at
        ``(page_table[b, length // ps], length % ps)``, attends over the
        gathered pages under a ``position <= length`` mask, and returns
        ``(logits, cache_k, cache_v)`` — full-vocab next-token logits
        per slot.  Idle slots (zero table, length 0) compute scratch
        garbage the host ignores.  Under ``kv_dtype="int8"`` the scale
        pools ride along (quantize on write, dequant fused into the
        attention read) and the return grows to ``(logits, cache_k,
        cache_v, scale_k, scale_v)``."""
        import jax.numpy as jnp

        from . import layers as L

        cfg, plan = self.cfg, self.plan
        p = dict(zip(self.program.param_names, train_vals))
        ps = self.page_size
        B = tokens.shape[0]
        h = L.vocab_parallel_embedding(p["embed"], tokens[:, None], plan)
        h = h + jnp.take(p["pos_embed"], lengths, axis=0)[:, None]
        page_ids = jnp.take_along_axis(
            page_table, (lengths // ps)[:, None], axis=1)[:, 0]
        offs = lengths % ps
        kpos = jnp.arange(self.pages_per_seq * ps)
        seen = kpos[None, :] <= lengths[:, None]          # (B, T_max)
        scale = cfg.head_dim ** -0.5
        for i in range(cfg.n_layers):
            pre = "l%d_" % i
            a = L.layer_norm(h, p[pre + "ln1_scale"], p[pre + "ln1_bias"])
            a = L.copy_to_model(a, plan)
            q = jnp.einsum("btd,dhe->bthe", a, p[pre + "wq"])
            k = jnp.einsum("btd,dhe->bthe", a, p[pre + "wk"])
            v = jnp.einsum("btd,dhe->bthe", a, p[pre + "wv"])
            if self.kv_quantized:
                kc, ksc = _kv_quant(k[:, 0], jnp)
                vc, vsc = _kv_quant(v[:, 0], jnp)
                if DECODE_WRITE_KV:
                    cache_k = cache_k.at[i, page_ids, offs].set(kc)
                    cache_v = cache_v.at[i, page_ids, offs].set(vc)
                    scale_k = scale_k.at[i, page_ids, offs].set(ksc)
                    scale_v = scale_v.at[i, page_ids, offs].set(vsc)
                kseq = _kv_dequant(
                    cache_k[i][page_table],
                    scale_k[i][page_table], jnp).reshape(
                    B, -1, self.heads_local, cfg.head_dim)
                vseq = _kv_dequant(
                    cache_v[i][page_table],
                    scale_v[i][page_table], jnp).reshape(
                    B, -1, self.heads_local, cfg.head_dim)
            else:
                if DECODE_WRITE_KV:
                    cache_k = cache_k.at[i, page_ids, offs].set(k[:, 0])
                    cache_v = cache_v.at[i, page_ids, offs].set(v[:, 0])
                kseq = cache_k[i][page_table].reshape(
                    B, -1, self.heads_local, cfg.head_dim)
                vseq = cache_v[i][page_table].reshape(
                    B, -1, self.heads_local, cfg.head_dim)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kseq) * scale
            s = jnp.where(seen[:, None, None, :], s, _NEG_INF)
            o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1),
                           vseq)
            o = jnp.einsum("bthe,hed->btd", o, p[pre + "wo"])
            h = h + L.row_parallel_out(o, plan)
            m = L.layer_norm(h, p[pre + "ln2_scale"], p[pre + "ln2_bias"])
            m = L.copy_to_model(m, plan)
            f = L.column_parallel_dense(m, p[pre + "w1"], p[pre + "b1"])
            f = jax.nn.gelu(f)
            f = f @ p[pre + "w2"]
            h = h + L.row_parallel_out(f, plan, bias=p[pre + "b2"])
        hf = L.layer_norm(h, p["lnf_scale"], p["lnf_bias"])
        hf = L.copy_to_model(hf, plan)
        logits = _full_logits((hf @ p["w_out"])[:, 0], plan)
        if self.kv_quantized:
            return logits, cache_k, cache_v, scale_k, scale_v
        return logits, cache_k, cache_v

    def _causal_attention(self, q, k, v):
        from ..parallel.ring_attention import local_attention
        return local_attention(q, k, v, causal=True)

    # -- runtime ------------------------------------------------------------
    def build_runtime_fns(self, mesh=None):
        """``(prefill_fn, decode_fn)`` — the jitted programs the
        DecodeRunner dispatches.  With a collapsed plan they are plain
        jits; with a model axis they are ``shard_map`` programs over
        ``mesh`` (params ride their partition specs, the cache pools
        shard their head dim, tokens/lengths/page tables and the
        all-gathered logits are replicated).  Both donate the cache
        pools so the update happens in place in HBM.  Under
        ``kv_dtype="int8"`` both fns take the scale pools positionally
        right after the code pools — ``(train_vals, cache_k, cache_v,
        scale_k, scale_v, ...)`` — donate them too, and return the
        5-tuple."""
        from jax.sharding import PartitionSpec as P

        if self.kv_quantized:
            def prefill_part(train_vals, cache_k, cache_v, scale_k,
                             scale_v, page_table, tokens, lengths):
                return self.prefill_replica(
                    train_vals, cache_k, cache_v, page_table, tokens,
                    lengths, scale_k=scale_k, scale_v=scale_v)

            def decode_part(train_vals, cache_k, cache_v, scale_k,
                            scale_v, page_table, lengths, tokens):
                return self.decode_replica(
                    train_vals, cache_k, cache_v, page_table, lengths,
                    tokens, scale_k=scale_k, scale_v=scale_v)

            donate = (1, 2, 3, 4)
        else:
            prefill_part = self.prefill_replica
            decode_part = self.decode_replica
            donate = (1, 2)
        if not self.plan.present("model"):
            prefill = jax.jit(prefill_part, donate_argnums=donate)
            decode = jax.jit(decode_part, donate_argnums=donate)
            return prefill, decode
        if mesh is None:
            mesh = self.plan.build_mesh()
        from ..parallel.ring_attention import _shard_map
        param_specs = tuple(self.program.partition_spec(n)
                            for n in self.program.param_names)
        # the scale pool keeps the cache pool's rank (trailing 1 in
        # place of head_dim) so the code-pool spec shards both
        cache = P(None, None, None, "model", None)
        if self.kv_quantized:
            in_specs = (param_specs, cache, cache, cache, cache,
                        P(), P(), P())
            out_specs = (P(), cache, cache, cache, cache)
        else:
            in_specs = (param_specs, cache, cache, P(), P(), P())
            out_specs = (P(), cache, cache)
        prefill = jax.jit(_shard_map(
            prefill_part, mesh, in_specs=in_specs,
            out_specs=out_specs), donate_argnums=donate)
        decode = jax.jit(_shard_map(
            decode_part, mesh, in_specs=in_specs,
            out_specs=out_specs), donate_argnums=donate)
        return prefill, decode

    # -- analysis -----------------------------------------------------------
    def decode_avals(self, n_pages, slots):
        """Local abstract values of one decode step, in the runtime
        decode fn's argument order — what the ``decode_step`` budget
        model traces with ``make_jaxpr(axis_env=...)``.  Under
        ``kv_dtype="int8"`` the pools are int8 and the f32 scale pools
        follow them (the ``build_runtime_fns`` wrapper order)."""
        from jax import ShapeDtypeStruct as S
        import jax.numpy as jnp
        params = tuple(
            S(self.program.local_shape(n), jnp.float32)
            for n in self.program.param_names)
        table = S((slots, self.pages_per_seq), jnp.int32)
        ints = S((slots,), jnp.int32)
        if self.kv_quantized:
            cache = S(self.cache_shape(n_pages), jnp.int8)
            scales = S(self.scale_shape(n_pages), jnp.float32)
            return (params, cache, cache, scales, scales,
                    table, ints, ints)
        cache = S(self.cache_shape(n_pages), jnp.float32)
        return (params, cache, cache, table, ints, ints)

    def describe(self):
        return {"config": self.cfg.describe(),
                "plan": self.plan.describe(),
                "page_size": self.page_size,
                "pages_per_seq": self.pages_per_seq,
                "kv_dtype": self.kv_dtype,
                "bytes_per_page": self.bytes_per_page()}
