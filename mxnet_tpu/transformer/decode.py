"""KV-cached autoregressive decode over the PR-14 MeshProgram.

The serve-side twin of :mod:`mxnet_tpu.transformer.model`: the SAME
parameter layout, initializer and per-layer math as
``MeshProgram.loss_replica``, refactored into the two phases an
autoregressive server actually runs —

- :meth:`DecodeProgram.prefill_replica`: one full causal forward over a
  length-bucketed prompt, writing every position's K/V into the paged
  cache and returning the last real position's next-token logits.
  Causality makes bucket padding *exact*: a padded key at position
  ``>= length`` is only visible to queries at positions ``>= length``,
  so real-position logits are bitwise independent of the bucket chosen
  (the padding-equivalence test in tests/test_decode.py).
- :meth:`DecodeProgram.decode_replica`: one token step for a fixed
  batch of sequence slots — embed the last token, write its K/V at
  ``page_table[b, length // page_size], length % page_size``, attend
  over the gathered per-sequence pages with a ``position <= length``
  mask, and emit full-vocab logits (the model-axis shards all-gathered;
  the vocab is tiny next to the cache).

**Paged cache layout** (docs/serving.md has the full picture): one pool
per model rank, ``(n_layers, n_pages, page_size, heads_local,
head_dim)`` for K and V each — a *page* holds ``page_size`` tokens of
K+V across ALL layers, so the host allocator hands out whole-sequence
page lists and admission control counts pages, not worst-case
sequences.  Page 0 is the reserved scratch page: idle batch slots carry
all-zero page tables and a sequence that overruns its allocation writes
(and reads) scratch — corruption of live sequences is impossible by
construction, the host side merely must not *trust* tokens past the
allocation (DecodeBatcher stops at ``max_new_tokens``).

Both phases are spelled ONCE (the ``parallel/zero.py`` discipline):
:meth:`build_runtime_fns` jits them (under ``shard_map`` when the plan
keeps a model axis), and the same bound methods feed
``jax.make_jaxpr(axis_env=plan.axis_env())`` in the ``decode_step``
budget model — the executed decode and the proven decode can never
drift.

``DECODE_WRITE_KV`` is the tier's **mutation seam** (the ``TP_ROW_PSUM``
discipline): flipping it False skips the cache write — the classic
stale-KV bug where every decode step attends over a cache missing its
own token — and the ``decode_step`` budget gate must fail rc=2 with the
cached-vs-full-forward mismatch named (tests/test_decode.py,
subprocess).  Production code never touches it.
"""
from __future__ import annotations

import numpy as _np

import jax

from ..parallel.mesh import MeshPlan
from .model import MeshProgram, TransformerLMConfig

__all__ = ["DecodeProgram", "DECODE_WRITE_KV"]

# runtime+analysis mutation seam (module docstring) — tests only
DECODE_WRITE_KV = True

_NEG_INF = -1e30


def _full_logits(logits_local, plan):
    """All-gather vocab-sharded ``(B, V/Km)`` logits over ``model`` into
    the replicated ``(B, V)`` row every rank can argmax.  The one place
    decode pays a vocab-sized collective — cheap by design (the vocab is
    tiny next to the KV pages) and absent when the axis collapses."""
    from jax import lax
    if plan.present("model"):
        return lax.all_gather(logits_local, "model", axis=1, tiled=True)
    return logits_local


class DecodeProgram:
    """One ``(config, plan)`` pair's concrete KV-cached decode program.

    ``plan`` may keep only the ``model`` axis: batch is a host concern
    (continuous batching joins/leaves slots per step) and the sequence
    dimension lives in the cache, so ``data``/``sequence`` must be
    collapsed.  ``page_size`` fixes the token-block granularity; the
    per-sequence page-table width is ``seq_len / page_size`` (a full
    sequence's worth of slots, unallocated tails pointing at scratch).
    """

    def __init__(self, cfg, plan=None, page_size=8):
        if not isinstance(cfg, TransformerLMConfig):
            cfg = TransformerLMConfig(**cfg)
        plan = MeshPlan.coerce(plan) or MeshPlan(data=1)
        plan = plan.resolve(1) if plan.data is None else plan
        if plan.size("data") != 1 or plan.size("sequence") != 1:
            raise ValueError(
                "DecodeProgram serves over the model axis only (batch is "
                "the host's continuous-batching concern, sequence lives "
                "in the cache); got %r" % (plan,))
        if cfg.seq_len % int(page_size):
            raise ValueError(
                "page_size %d must divide seq_len %d"
                % (page_size, cfg.seq_len))
        self.cfg = cfg
        self.plan = plan
        self.program = MeshProgram(cfg, plan)
        self.page_size = int(page_size)
        self.pages_per_seq = cfg.seq_len // self.page_size
        self.heads_local = cfg.n_heads // plan.size("model")

    # -- geometry ----------------------------------------------------------
    def cache_shape(self, n_pages):
        """LOCAL (per model rank) K or V pool shape."""
        return (self.cfg.n_layers, int(n_pages), self.page_size,
                self.heads_local, self.cfg.head_dim)

    def global_cache_shape(self, n_pages):
        return (self.cfg.n_layers, int(n_pages), self.page_size,
                self.cfg.n_heads, self.cfg.head_dim)

    def bytes_per_page(self):
        """GLOBAL f32 bytes one page pins across all model ranks: K+V for
        ``page_size`` tokens through every layer — the unit the page
        allocator and pages-based fleet admission count in."""
        cfg = self.cfg
        return (2 * cfg.n_layers * self.page_size * cfg.n_heads
                * cfg.head_dim * 4)

    def pages_for(self, n_tokens):
        """Pages a sequence of ``n_tokens`` total (prompt + generation
        budget) pins, capped nowhere — callers check against the pool."""
        return -(-int(n_tokens) // self.page_size)

    # -- the per-replica phases (spelled ONCE) ------------------------------
    def prefill_replica(self, train_vals, cache_k, cache_v, page_table,
                        tokens, lengths):
        """Full causal forward over a ``(B, Tb)`` padded prompt bucket:
        returns ``(logits, cache_k, cache_v)`` with the last *real*
        position's full-vocab next-token logits and every position's K/V
        scattered into ``page_table``'s pages (page-table tails of 0
        land in scratch — see the module docstring).  ``Tb`` must be a
        page multiple (the bucket ladder is built that way)."""
        import jax.numpy as jnp

        from . import layers as L

        cfg, plan = self.cfg, self.plan
        p = dict(zip(self.program.param_names, train_vals))
        B, Tb = tokens.shape
        ps = self.page_size
        h = L.vocab_parallel_embedding(p["embed"], tokens, plan)
        h = h + p["pos_embed"][:Tb][None]
        ks, vs = [], []
        for i in range(cfg.n_layers):
            pre = "l%d_" % i
            a = L.layer_norm(h, p[pre + "ln1_scale"], p[pre + "ln1_bias"])
            a = L.copy_to_model(a, plan)
            q = jnp.einsum("btd,dhe->bthe", a, p[pre + "wq"])
            k = jnp.einsum("btd,dhe->bthe", a, p[pre + "wk"])
            v = jnp.einsum("btd,dhe->bthe", a, p[pre + "wv"])
            ks.append(k)
            vs.append(v)
            o = self._causal_attention(q, k, v)
            o = jnp.einsum("bthe,hed->btd", o, p[pre + "wo"])
            h = h + L.row_parallel_out(o, plan)
            m = L.layer_norm(h, p[pre + "ln2_scale"], p[pre + "ln2_bias"])
            m = L.copy_to_model(m, plan)
            f = L.column_parallel_dense(m, p[pre + "w1"], p[pre + "b1"])
            f = jax.nn.gelu(f)
            f = f @ p[pre + "w2"]
            h = h + L.row_parallel_out(f, plan, bias=p[pre + "b2"])
        # next-token logits of the last real position only: slice the
        # hidden state BEFORE the vocab projection so the bucket tail
        # never pays the matmul
        last = jnp.take_along_axis(
            h, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)
        hf = L.layer_norm(last, p["lnf_scale"], p["lnf_bias"])
        hf = L.copy_to_model(hf, plan)
        logits = _full_logits((hf @ p["w_out"])[:, 0], plan)
        # scatter the prompt K/V into pages: bucket position t lands at
        # (page_table[b, t // ps], t % ps); unallocated table tails are
        # 0 and land in scratch
        npg = Tb // ps
        pages = page_table[:, :npg]
        kp = jnp.stack(ks).reshape(
            cfg.n_layers, B, npg, ps, self.heads_local, cfg.head_dim)
        vp = jnp.stack(vs).reshape(
            cfg.n_layers, B, npg, ps, self.heads_local, cfg.head_dim)
        if DECODE_WRITE_KV:
            cache_k = cache_k.at[:, pages].set(kp)
            cache_v = cache_v.at[:, pages].set(vp)
        return logits, cache_k, cache_v

    def decode_replica(self, train_vals, cache_k, cache_v, page_table,
                       lengths, tokens):
        """One token step for every batch slot: ``tokens (B,)`` are the
        slots' last tokens, ``lengths (B,)`` the cached token counts (=
        the new token's position).  Writes the new K/V at
        ``(page_table[b, length // ps], length % ps)``, attends over the
        gathered pages under a ``position <= length`` mask, and returns
        ``(logits, cache_k, cache_v)`` — full-vocab next-token logits
        per slot.  Idle slots (zero table, length 0) compute scratch
        garbage the host ignores."""
        import jax.numpy as jnp

        from . import layers as L

        cfg, plan = self.cfg, self.plan
        p = dict(zip(self.program.param_names, train_vals))
        ps = self.page_size
        B = tokens.shape[0]
        h = L.vocab_parallel_embedding(p["embed"], tokens[:, None], plan)
        h = h + jnp.take(p["pos_embed"], lengths, axis=0)[:, None]
        page_ids = jnp.take_along_axis(
            page_table, (lengths // ps)[:, None], axis=1)[:, 0]
        offs = lengths % ps
        kpos = jnp.arange(self.pages_per_seq * ps)
        seen = kpos[None, :] <= lengths[:, None]          # (B, T_max)
        scale = cfg.head_dim ** -0.5
        for i in range(cfg.n_layers):
            pre = "l%d_" % i
            a = L.layer_norm(h, p[pre + "ln1_scale"], p[pre + "ln1_bias"])
            a = L.copy_to_model(a, plan)
            q = jnp.einsum("btd,dhe->bthe", a, p[pre + "wq"])
            k = jnp.einsum("btd,dhe->bthe", a, p[pre + "wk"])
            v = jnp.einsum("btd,dhe->bthe", a, p[pre + "wv"])
            if DECODE_WRITE_KV:
                cache_k = cache_k.at[i, page_ids, offs].set(k[:, 0])
                cache_v = cache_v.at[i, page_ids, offs].set(v[:, 0])
            kseq = cache_k[i][page_table].reshape(
                B, -1, self.heads_local, cfg.head_dim)
            vseq = cache_v[i][page_table].reshape(
                B, -1, self.heads_local, cfg.head_dim)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kseq) * scale
            s = jnp.where(seen[:, None, None, :], s, _NEG_INF)
            o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1),
                           vseq)
            o = jnp.einsum("bthe,hed->btd", o, p[pre + "wo"])
            h = h + L.row_parallel_out(o, plan)
            m = L.layer_norm(h, p[pre + "ln2_scale"], p[pre + "ln2_bias"])
            m = L.copy_to_model(m, plan)
            f = L.column_parallel_dense(m, p[pre + "w1"], p[pre + "b1"])
            f = jax.nn.gelu(f)
            f = f @ p[pre + "w2"]
            h = h + L.row_parallel_out(f, plan, bias=p[pre + "b2"])
        hf = L.layer_norm(h, p["lnf_scale"], p["lnf_bias"])
        hf = L.copy_to_model(hf, plan)
        logits = _full_logits((hf @ p["w_out"])[:, 0], plan)
        return logits, cache_k, cache_v

    def _causal_attention(self, q, k, v):
        from ..parallel.ring_attention import local_attention
        return local_attention(q, k, v, causal=True)

    # -- runtime ------------------------------------------------------------
    def build_runtime_fns(self, mesh=None):
        """``(prefill_fn, decode_fn)`` — the jitted programs the
        DecodeRunner dispatches.  With a collapsed plan they are plain
        jits; with a model axis they are ``shard_map`` programs over
        ``mesh`` (params ride their partition specs, the cache pools
        shard their head dim, tokens/lengths/page tables and the
        all-gathered logits are replicated).  Both donate the cache
        pools so the update happens in place in HBM."""
        from jax.sharding import PartitionSpec as P

        if not self.plan.present("model"):
            prefill = jax.jit(self.prefill_replica,
                              donate_argnums=(1, 2))
            decode = jax.jit(self.decode_replica, donate_argnums=(1, 2))
            return prefill, decode
        if mesh is None:
            mesh = self.plan.build_mesh()
        from ..parallel.ring_attention import _shard_map
        param_specs = tuple(self.program.partition_spec(n)
                            for n in self.program.param_names)
        cache = P(None, None, None, "model", None)
        prefill = jax.jit(_shard_map(
            self.prefill_replica, mesh,
            in_specs=(param_specs, cache, cache, P(), P(), P()),
            out_specs=(P(), cache, cache)), donate_argnums=(1, 2))
        decode = jax.jit(_shard_map(
            self.decode_replica, mesh,
            in_specs=(param_specs, cache, cache, P(), P(), P()),
            out_specs=(P(), cache, cache)), donate_argnums=(1, 2))
        return prefill, decode

    # -- analysis -----------------------------------------------------------
    def decode_avals(self, n_pages, slots):
        """Local abstract values of one decode step, in
        ``decode_replica`` argument order — what the ``decode_step``
        budget model traces with ``make_jaxpr(axis_env=...)``."""
        from jax import ShapeDtypeStruct as S
        import jax.numpy as jnp
        params = tuple(
            S(self.program.local_shape(n), jnp.float32)
            for n in self.program.param_names)
        cache = S(self.cache_shape(n_pages), jnp.float32)
        return (params, cache, cache,
                S((slots, self.pages_per_seq), jnp.int32),
                S((slots,), jnp.int32), S((slots,), jnp.int32))

    def describe(self):
        return {"config": self.cfg.describe(),
                "plan": self.plan.describe(),
                "page_size": self.page_size,
                "pages_per_seq": self.pages_per_seq,
                "bytes_per_page": self.bytes_per_page()}
