"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

Built from scratch on JAX/XLA/Pallas (see SURVEY.md for the blueprint):
XLA replaces the dependency engine + graph executor + memory planner of the
reference (yjxiong/mxnet), Pallas kernels replace CUDA/cuDNN ops, and
ICI/DCN collectives replace the NCCL/ps-lite KVStore backends.

Import as ``import mxnet_tpu as mx`` — the public surface mirrors the
reference's ``mx.*`` namespaces.
"""
from __future__ import annotations

import os as _os

# honor JAX_PLATFORMS even when a site plugin force-registered a hardware
# backend through jax.config (which outranks the env var): pin it back so
# `JAX_PLATFORMS=cpu python script.py` behaves as documented
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax
    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

# join a jax.distributed cluster from the env tools/launch.py sets — must
# happen before anything touches a jax backend, hence at import
if _os.environ.get("JAX_COORDINATOR_ADDRESS") and \
        _os.environ.get("JAX_NUM_PROCESSES") and \
        _os.environ.get("JAX_PROCESS_ID"):
    import jax as _jax
    try:
        _jax.distributed.initialize(
            coordinator_address=_os.environ["JAX_COORDINATOR_ADDRESS"],
            num_processes=int(_os.environ["JAX_NUM_PROCESSES"]),
            process_id=int(_os.environ["JAX_PROCESS_ID"]))
    except Exception as _e:  # already initialized / misconfigured
        import warnings as _warnings
        _warnings.warn("jax.distributed.initialize failed: %s" % (_e,))

__version__ = "0.1.0"

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus
from . import base
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor
from . import analysis
from . import autograd
from . import random
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import kvstore
from . import kvstore as kv
from . import io
from . import recordio
from . import image
from . import model
from . import module
from . import module as mod
from . import callback
from . import monitor
from .monitor import Monitor
from . import visualization
from . import visualization as viz
from . import rnn
from . import gluon
from . import parallel
from . import profiler
from . import telemetry
from . import engine
from . import rtc
from . import contrib
from . import serving
from . import operator
from . import kvstore_server
from . import attribute
from .attribute import AttrScope
from . import name
from . import test_utils
