"""Host-only mlops micro-bench: ``python -m mxnet_tpu.mlops.bench``.

Run by ``bench.py``'s ``mlops`` stage as a ``JAX_PLATFORMS=cpu``
subprocess BEFORE backend acquisition (the r05 pattern), so the numbers
stay live when the TPU is down.  Prints ONE JSON line:

- ``simulator_accuracy_pct`` — fidelity of the discrete-event fleet
  simulator vs the *real* host serving path: the same seeded burst is
  run through a live Runner→Batcher and through
  :class:`~mxnet_tpu.mlops.simulator.FleetSimulator` with service times
  calibrated from a separate warmup measurement; accuracy =
  ``100 - max relative error`` over reqs/sec and per-tier p99.  The
  documented tolerance is <= 15 % error (accuracy >= 85), asserted
  tier-1 in tests/test_mlops.py.
- ``promotion_decision_ms`` — wall time of one full promotion decision
  tick (golden parity + registry scrape + judge + audit write + hot
  swap) on the terminal promote of a real train→canary→promote cycle.
- ``capacity_replicas_for_1m_dau`` — the deterministic capacity answer:
  replicas needed for 1M DAU at the pinned gold SLO under the pinned
  service-time model (no measured inputs — byte-identical on any host,
  which is what lets bench_compare gate it with near-zero slack).
- ``simulator_events_per_sec`` — raw simulator throughput (how cheap a
  capacity question is to ask).

Wall-clock use in this file is measurement of the thing under test, not
promotion decision logic — the inline SRV005 disables mark exactly those
lines (the sweep keeps the rest of the package honest).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def _build_runner(buckets=(1, 4, 16), feat=32, hidden=64, seed=0):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.serving import ModelRunner

    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return ModelRunner(net, buckets=buckets, example_shape=(feat,),
                       warmup=True)


def _calibrate_service_ms(runner, batch_timeout_ms=1.0, repeats=5):
    """Measured per-bucket service time through a REAL batcher (median
    of ``repeats``, coalescing window subtracted) — the calibration
    input the simulator's validation contract allows: a *separate*
    measurement of the same pipeline, never the run being predicted.
    Going through the batcher (not bare ``forward_batch``) folds the
    per-batch stack/split/stats overhead into the service time, which is
    exactly what the simulated batches cost too."""
    from mxnet_tpu.serving.batcher import Batcher

    b = Batcher(runner, batch_timeout_ms=batch_timeout_ms, max_queue=512)
    x = np.zeros(runner.example_shape, np.float32)
    b.infer(x, timeout=30)   # warm the path outside any timed window
    table = {}
    for bucket in runner.buckets:
        if bucket == runner.max_batch:
            continue   # calibrated under load below
        # a partial bucket waits out the full coalescing window before
        # executing; subtract it
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()  # mxlint: disable=SRV005 — calibration measurement
            futs = [b.submit(x) for _ in range(bucket)]
            for f in futs:
                f.result(30)
            dt = (time.perf_counter() - t0) * 1e3  # mxlint: disable=SRV005
            times.append(max(dt - batch_timeout_ms, 0.01))
        table[bucket] = sorted(times)[len(times) // 2]
    # the max bucket — what a sustained burst actually runs — is
    # calibrated under a deep queue (8 back-to-back full batches), so
    # submit-thread GIL contention and deep-heap admission costs land in
    # the figure exactly as they do in the predicted run
    n_cal = 8 * runner.max_batch
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()  # mxlint: disable=SRV005 — calibration measurement
        futs = [b.submit(x) for _ in range(n_cal)]
        for f in futs:
            f.result(30)
        dt = (time.perf_counter() - t0) * 1e3  # mxlint: disable=SRV005
        times.append(max((dt - batch_timeout_ms) / 8.0, 0.01))
    table[runner.max_batch] = sorted(times)[len(times) // 2]
    b.drain()
    return table


def _parked_burst(runner, n_requests, batch_timeout_ms=1.0):
    """One real bench-fleet run in the parked-worker pattern the fleet
    chaos tests pin (deterministic structure on a 1-core host): the
    worker is gated inside a primer batch, the whole tiered burst queues
    behind it, the gate opens, and the backlog drains in (tier,
    deadline, arrival) order.  Returns ``(arrivals, free_ms, report)``
    where ``free_ms`` is when the server came free for the backlog —
    the instant the simulator models via ``server_free_at_ms``."""
    import threading

    from mxnet_tpu.serving.batcher import Batcher

    gate = threading.Event()
    released = [None]
    orig = runner.forward_batch
    first = [True]

    def gated(x):
        if first[0]:
            first[0] = False
            gate.wait(60)
            out = orig(x)
            released[0] = time.perf_counter()  # mxlint: disable=SRV005 — measuring the real run
            return out
        return orig(x)

    runner.forward_batch = gated
    try:
        batcher = Batcher(runner, batch_timeout_ms=batch_timeout_ms,
                          max_queue=max(1024, n_requests))
        rng = np.random.RandomState(0)
        examples = rng.rand(64, runner.example_shape[0]) \
            .astype(np.float32)
        tiers = ["gold", "silver", "bronze"]
        t0 = time.perf_counter()  # mxlint: disable=SRV005 — measuring the real run
        batcher.submit(examples[0], tier="gold")   # the parked primer
        deadline = t0 + 30.0
        while batcher._batch_started is None:
            if time.perf_counter() > deadline:  # mxlint: disable=SRV005 — watchdog on the real run
                raise RuntimeError("worker never parked in the primer")
            time.sleep(0.0005)  # mxlint: disable=SRV005 — polling the real run
        arrivals = []
        for i in range(n_requests):
            tier = tiers[i % 3]
            t_sub = (time.perf_counter() - t0) * 1e3  # mxlint: disable=SRV005
            batcher.submit(examples[0], tier=tier)
            arrivals.append((t_sub, tier, None))
        gate.set()
        batcher.drain(timeout=240)
        t_end = time.perf_counter()  # mxlint: disable=SRV005 — measuring the real run
        free_ms = (released[0] - t0) * 1e3
        drain_ms = (t_end - released[0]) * 1e3
        report = {
            "free_ms": free_ms,
            "batches": batcher.stats.batches_total - 1,   # minus primer
            "drain_ms": drain_ms,
            "reqs_per_sec": n_requests / (drain_ms / 1e3),
            "tiers": {t: batcher.stats.tier_latency_ms(t)
                      for t in tiers},
        }
        return arrivals, free_ms, report
    finally:
        runner.forward_batch = orig


def _validate_pair(runner, partial, n_requests, buckets):
    """One tightly-paired (calibrate, predict) round: a calibration
    burst immediately followed by the predicted burst, so host drift
    hits both sides of the pair equally.  Returns the error dict."""
    from mxnet_tpu.mlops.simulator import FleetSimulator, SimConfig

    _, _, cal = _parked_burst(runner, n_requests)
    table = dict(partial)
    table[runner.max_batch] = cal["drain_ms"] / max(1, cal["batches"])
    arrivals, free_ms, real = _parked_burst(runner, n_requests)
    cfg = SimConfig(service_ms=lambda bucket: table[bucket],
                    buckets=buckets, batch_timeout_ms=1.0,
                    max_queue=max(1024, n_requests))
    sim = FleetSimulator(cfg, replicas=1).run(
        arrivals, server_free_at_ms=free_ms)
    # sim reqs/sec over the drain span (release -> last completion), the
    # same denominator the real report uses
    t0 = min(t for t, _, _ in arrivals)
    sim_drain_ms = (sim["span_ms"] + t0) - free_ms
    sim_rps = n_requests / (sim_drain_ms / 1e3)
    errs = {"reqs_per_sec": abs(sim_rps - real["reqs_per_sec"])
            / max(real["reqs_per_sec"], 1e-9)}
    for tier in ("gold", "silver", "bronze"):
        sim_p99 = sim["tiers"].get(tier, {}).get("p99_ms", 0.0)
        real_p99 = real["tiers"][tier][1]
        errs["%s_p99" % tier] = abs(sim_p99 - real_p99) \
            / max(real_p99, 1e-9)
    return errs, real, sim_rps


def simulator_validation(n_requests=240, buckets=(1, 4, 16), feat=64,
                         hidden=256, repeats=5):
    """Real parked bursts vs their simulation; returns the accuracy
    keys.

    ``repeats`` tightly-interleaved (calibration burst, predicted
    burst) pairs of the identical workload: each calibration run sets
    the per-batch service time ((drain wall) / batches — contention and
    batcher overhead included) and the run right after it is predicted.
    The reported accuracy is the MEDIAN pair's (the repo's interleaved
    min/median-of-N discipline: a single load spike on a 1-core CI host
    would otherwise poison one side of one pair and read as simulator
    error).  Accuracy is judged on reqs/sec and per-tier p99
    (documented tolerance: every error <= 15 %).

    ``simulator_best_*`` report the BEST pair (the min-of-N side of the
    same discipline): under sustained 2x CPU load every pair's median
    can be poisoned, but a load spike that hits all 5 interleaved
    pairs' calibrate/predict windows asymmetrically is not a simulator
    error — tier-1 asserts the best pair, the bench gate trends the
    median keys."""
    runner = _build_runner(buckets=buckets, feat=feat, hidden=hidden)
    partial = _calibrate_service_ms(runner, batch_timeout_ms=1.0)
    pairs = [_validate_pair(runner, partial, n_requests, buckets)
             for _ in range(int(repeats))]
    pairs.sort(key=lambda p: max(p[0].values()))
    best_errs = pairs[0][0]                        # the best pair
    errs, real, sim_rps = pairs[len(pairs) // 2]   # the median pair
    worst = max(errs, key=lambda k: errs[k])
    return {
        "simulator_accuracy_pct": round(100.0 * (1.0 - errs[worst]), 2),
        "simulator_worst_metric": worst,
        "simulator_real_reqs_per_sec": round(real["reqs_per_sec"], 2),
        "simulator_sim_reqs_per_sec": round(sim_rps, 2),
        "simulator_errors_pct": {k: round(100 * v, 2)
                                 for k, v in sorted(errs.items())},
        "simulator_best_accuracy_pct": round(
            100.0 * (1.0 - max(best_errs.values())), 2),
        "simulator_best_errors_pct": {k: round(100 * v, 2)
                                      for k, v in sorted(
                                          best_errs.items())},
        "simulator_pair_accuracies_pct": [
            round(100.0 * (1.0 - max(e.values())), 2)
            for e, _, _ in pairs],
    }


def promotion_cycle(feat=16):
    """A real train→checkpoint→canary→promote cycle; returns the
    decision-latency key (the terminal promote tick, measured)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.mlops import (PromotionController,
                                 runner_from_trainer_checkpoint)
    from mxnet_tpu.parallel import DataParallelTrainer
    from mxnet_tpu.serving import ModelFleet

    def build_net():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.Dense(4))
        return net

    def train(seed, steps, ckdir, run_id):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = build_net()
        net.initialize(mx.init.Xavier())
        trainer = DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05}, run_id=run_id)
        rng = np.random.RandomState(seed)
        for i in range(steps):
            trainer.step(mx.nd.array(rng.rand(8, feat).astype(np.float32)),
                         mx.nd.array(rng.randint(0, 4, 8).astype(np.int64)))
        trainer.flush()
        trainer.save_checkpoint(ckdir, epoch=0, nbatch=steps)

    root = tempfile.mkdtemp(prefix="mxtpu_mlops_bench_")
    try:
        ck_inc = os.path.join(root, "incumbent")
        ck_watch = os.path.join(root, "watch")
        train(0, 2, ck_inc, "bench-incumbent")

        def factory(path, rec):
            return runner_from_trainer_checkpoint(
                rec, build_net, example_shape=(feat,), buckets=(1, 4))

        from mxnet_tpu.resilience.checkpoint import latest_checkpoint
        inc_runner, _ = factory(*latest_checkpoint(ck_inc))
        fleet = ModelFleet(batch_timeout_ms=0.5)
        fleet.register("model", inc_runner,
                       tier_slos={"gold": 10000.0},
                       service_time_hint_ms=5.0)
        rng = np.random.RandomState(1)
        golden = rng.rand(16, feat).astype(np.float32)
        ctrl = PromotionController(
            fleet, "model", ck_watch, factory, golden=golden,
            audit_dir=os.path.join(root, "audit"),
            schedule=(0.5,), min_stage_requests=8,
            # one optimizer step apart: high-but-not-total parity is
            # expected; the bench judges decision latency, not the model
            parity_threshold=0.5,
            register_kwargs={"service_time_hint_ms": 5.0})
        train(0, 3, ck_watch, "bench-candidate")
        ctrl.poll()
        X = rng.rand(64, feat).astype(np.float32)
        for i in range(64):
            fleet.infer(X[i % 64], model="model", request_id=i, timeout=30)
        t0 = time.perf_counter()  # mxlint: disable=SRV005 — measuring the controller under test
        rec = ctrl.evaluate()
        decision_ms = (time.perf_counter() - t0) * 1e3  # mxlint: disable=SRV005
        fleet.drain()
        ok = rec is not None and rec["decision"]["decision"] == "promote"
        return {
            "promotion_decision_ms": round(decision_ms, 3),
            "promotion_cycle_ok": bool(ok),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


# the pinned capacity scenario: 1M DAU, 20 requests/user/day, diurnal
# peak 2x, judged on a 20 s crest window; service model pinned (32 ms
# per max bucket of 8) so the answer is byte-identical on any host
CAPACITY_DAU = 1_000_000
CAPACITY_GOLD_SLO_MS = 250.0
_CAPACITY_SERVICE_MS = {1: 8.0, 4: 18.0, 8: 32.0}


def capacity_answer():
    from mxnet_tpu.mlops.simulator import (SimConfig, required_replicas,
                                           trace_for_dau)

    cfg = SimConfig(service_ms=lambda b: _CAPACITY_SERVICE_MS[b],
                    buckets=(1, 4, 8), batch_timeout_ms=2.0,
                    max_queue=128)
    trace = trace_for_dau(CAPACITY_DAU, window_s=20.0, seed=0,
                          deadlines_ms={"gold": CAPACITY_GOLD_SLO_MS,
                                        "silver": 400.0, "bronze": 150.0})
    t0 = time.perf_counter()  # mxlint: disable=SRV005 — measuring simulator throughput
    replicas, report = required_replicas(
        cfg, trace, slo_tier="gold", slo_p99_ms=CAPACITY_GOLD_SLO_MS,
        max_shed_rate=0.0)
    dt = time.perf_counter() - t0  # mxlint: disable=SRV005
    return {
        "capacity_replicas_for_1m_dau": replicas,
        "capacity_trace_arrivals": report["arrivals"],
        "capacity_gold_p99_ms": report["tiers"]["gold"]["p99_ms"],
        "simulator_events_per_sec": round(report["arrivals"]
                                          / max(dt, 1e-9), 1),
    }


def main():
    out = {}
    out.update(simulator_validation())
    out.update(promotion_cycle())
    out.update(capacity_answer())
    print(json.dumps(out), flush=True)
    # the stage contract: the cycle promoted and the simulator held its
    # documented <= 15 % tolerance
    ok = out.get("promotion_cycle_ok") \
        and out.get("simulator_accuracy_pct", 0) >= 85.0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
