"""Deterministic discrete-event fleet simulator: capacity questions as
a gated computation.

The serving fleet's policies — deadline-aware coalescing, shed-before-rot
admission control, tier-ordered eviction, circuit breaking, degraded-mode
fallback — are all *deterministic arithmetic* (serving/batcher.py runs on
a pinned ``service_time_hint_ms`` exactly so chaos tests can replay shed
decisions byte-for-byte).  That makes the fleet simulable: this module
replays seeded traffic traces (diurnal + burst generators scaled to
millions of DAU) against the *modeled* policies on a virtual clock, with
per-batch service time taken from the PR-4 modeled cost
(:func:`service_ms_from_modeled_cost`) or calibrated from one real
measurement — so "how many replicas for 1M DAU at gold SLO?" is answered
by :func:`required_replicas` (tools/capacity.py) as a deterministic
computation, not a load-test guess.

Fidelity contract: the same admission arithmetic as the live Batcher
(``(position // max_batch + 1 + in_flight) * est_batch_ms`` vs deadline,
tier-ordered queue, worst-ranked eviction under a full queue, the
hopeless-request sweep before each batch), validated against the real
host serving bench within a documented tolerance (<= 15 % on reqs/sec
and per-tier p99 — asserted tier-1 in tests/test_mlops.py and reported
as ``simulator_accuracy_pct`` by the bench's ``mlops`` stage).

Everything runs on a virtual millisecond clock: no wall-clock reads (the
SRV005 sweep enforces this for the whole package) and no global RNG —
traces are built from seeded ``random.Random`` instances, so every
report is byte-identical for a fixed seed.
"""
from __future__ import annotations

import bisect
import heapq
import math
import random

from ..serving.batcher import tier_name, tier_rank

__all__ = ["SimConfig", "FleetSimulator", "SimReport",
           "diurnal_trace", "burst_trace", "trace_for_dau",
           "service_ms_from_modeled_cost", "token_ms_from_decode_step",
           "decode_service_model", "required_replicas",
           "percentile"]

# pinned reference throughput constants for converting the PR-4 modeled
# cost into host-free service times (a "capacity planning chip": the
# numbers only need to be *consistent*, budget-style, not measured —
# capacity answers gate on determinism, and real-host validation runs
# through the calibrated path instead)
DEFAULT_FLOPS_PER_S = 50e9
DEFAULT_BYTES_PER_S = 25e9
DEFAULT_OVERHEAD_MS = 1.0


def percentile(samples, q):
    """Nearest-rank percentile (the serving/stats.py convention, kept
    local so the simulator stays importable host-only)."""
    data = sorted(samples)
    if not data:
        return 0.0
    rank = max(0, min(len(data) - 1,
                      int(round(q / 100.0 * (len(data) - 1)))))
    return data[rank]


def service_ms_from_modeled_cost(cost_row, flops_per_s=DEFAULT_FLOPS_PER_S,
                                 bytes_per_s=DEFAULT_BYTES_PER_S,
                                 overhead_ms=DEFAULT_OVERHEAD_MS):
    """Modeled per-batch service time from one bucket's mxcost row
    (``ModelRunner.modeled_cost()[bucket]``): the roofline max of
    compute time and memory time plus a fixed dispatch overhead."""
    flops = float(cost_row.get("flops", 0))
    moved = float(cost_row.get("bytes_read", 0)
                  + cost_row.get("bytes_written", 0))
    return max(flops / flops_per_s, moved / bytes_per_s) * 1e3 \
        + float(overhead_ms)


def token_ms_from_decode_step(cost_row, flops_per_s=DEFAULT_FLOPS_PER_S,
                              bytes_per_s=DEFAULT_BYTES_PER_S,
                              overhead_ms=DEFAULT_OVERHEAD_MS,
                              kv_pool_bytes_f32=None,
                              kv_pool_bytes=None):
    """Modeled per-token step time for the decode tier from the
    ``decode_step`` budget row (STATIC_BUDGETS.json): one decode step
    advances EVERY slot by one token, so the roofline step time IS the
    per-token latency each active sequence observes — the unit the
    DecodeBatcher's tokens-remaining shed arithmetic prices in.

    The budget row models the f32 cache; a quantized KV pool changes
    the bytes the step streams, so callers sizing an int8 tier pass
    BOTH pool sizes (``kv_pool_bytes_f32`` as modeled in the row,
    ``kv_pool_bytes`` as deployed — codes + per-page scales) and the
    difference is swapped out of the moved-byte total before the
    roofline (docs/precision.md)."""
    row = dict(cost_row)
    if kv_pool_bytes is not None and kv_pool_bytes_f32:
        moved = float(row.get("bytes_read", 0))
        row["bytes_read"] = max(
            0.0, moved - float(kv_pool_bytes_f32) + float(kv_pool_bytes))
    return service_ms_from_modeled_cost(row, flops_per_s=flops_per_s,
                                        bytes_per_s=bytes_per_s,
                                        overhead_ms=overhead_ms)


def decode_service_model(token_ms, max_new_tokens, prefill_ms=0.0):
    """Token-level service model for an autoregressive tier: a
    ``bucket -> ms`` callable for :class:`SimConfig`.

    Under continuous batching a coalesced batch holds its slots for
    ``prefill + max_new_tokens x token_ms`` — the batch *fill* changes
    how many tokens are delivered, not the wall time (slots decode in
    lockstep, idle slots compute scratch) — which is exactly why token
    capacity questions need token-level service times instead of the
    fixed-shape per-bucket table: a request costs its token budget, not
    one forward."""
    svc = float(prefill_ms) + float(max_new_tokens) * float(token_ms)

    def service(bucket):
        return svc
    return service


# ---------------------------------------------------------------------------
# traffic traces
# ---------------------------------------------------------------------------
def _mixed(seq, tier_mix):
    """Deterministic tier for request ordinal ``seq`` under a mix like
    ``{"gold": 0.5, "silver": 0.3, "bronze": 0.2}`` — cycled by weight
    so every rerun sees the identical tier sequence."""
    # build the smallest repeating pattern once per mix
    names = sorted(tier_mix)
    weights = [tier_mix[n] for n in names]
    total = sum(weights)
    pattern = []
    counts = [0.0] * len(names)
    for _ in range(max(1, int(round(total * 20)) or 20)):
        # largest-remainder round-robin: deterministic, proportionate
        i = max(range(len(names)),
                key=lambda j: (weights[j] / total) * (len(pattern) + 1)
                - counts[j])
        counts[i] += 1
        pattern.append(names[i])
    return pattern[seq % len(pattern)]


def diurnal_trace(duration_s, mean_rps, seed=0,
                  tier_mix=None, deadlines_ms=None, peak_factor=2.0,
                  period_s=86400.0, phase_s=0.0):
    """Seeded open-loop arrivals with a sinusoidal diurnal envelope:
    instantaneous rate = ``mean_rps * (1 + (peak_factor-1)/(peak_factor+1)
    * sin(...))`` so the peak:mean ratio is ``peak_factor`` : 1 at the
    crest.  Returns ``[(t_ms, tier, deadline_ms), ...]`` sorted by time;
    byte-identical for a fixed seed."""
    tier_mix = tier_mix or {"gold": 0.2, "silver": 0.3, "bronze": 0.5}
    deadlines_ms = deadlines_ms or {"gold": 500.0, "silver": 250.0,
                                    "bronze": 100.0}
    rng = random.Random(int(seed))
    amp = (float(peak_factor) - 1.0) / (float(peak_factor) + 1.0)
    base = float(mean_rps) * (1.0 + amp)   # rate at the crest envelope
    out, t, seq = [], 0.0, 0
    horizon = float(duration_s) * 1000.0
    while True:
        # thinned Poisson process: draw at the crest rate, keep with
        # probability rate(t)/base — exact for inhomogeneous arrivals
        t += rng.expovariate(base) * 1000.0
        if t >= horizon:
            break
        frac = (t / 1000.0 + phase_s) / float(period_s)
        rate = float(mean_rps) * (1.0 + amp * math.sin(2 * math.pi * frac))
        if rng.random() * base > rate:
            continue
        tier = _mixed(seq, tier_mix)
        out.append((t, tier, deadlines_ms.get(tier)))
        seq += 1
    return out


def burst_trace(n, at_ms=0.0, tier_cycle=("gold", "silver", "bronze"),
                deadlines_ms=None, spacing_ms=0.0):
    """``n`` arrivals at/after ``at_ms`` cycling the given tiers — the
    overload burst (all at one instant when ``spacing_ms`` is 0)."""
    deadlines_ms = deadlines_ms or {}
    return [(float(at_ms) + i * float(spacing_ms),
             tier_cycle[i % len(tier_cycle)],
             deadlines_ms.get(tier_cycle[i % len(tier_cycle)]))
            for i in range(int(n))]


def trace_for_dau(dau, window_s=60.0, requests_per_user_per_day=20.0,
                  seed=0, at_peak=True, peak_factor=2.0, tier_mix=None,
                  deadlines_ms=None):
    """The millions-of-users scenario as a trace: ``dau`` daily active
    users at ``requests_per_user_per_day`` give a mean request rate;
    capacity planning simulates a ``window_s`` slice at the diurnal crest
    (``at_peak``) — the window the fleet must be provisioned for."""
    mean_rps = float(dau) * float(requests_per_user_per_day) / 86400.0
    return diurnal_trace(
        window_s, mean_rps, seed=seed, tier_mix=tier_mix,
        deadlines_ms=deadlines_ms, peak_factor=peak_factor,
        # phase the window onto the sine crest: sin = 1 at period/4
        phase_s=86400.0 / 4.0 - window_s / 2.0 if at_peak else 0.0)


# ---------------------------------------------------------------------------
# the simulator proper
# ---------------------------------------------------------------------------
class SimConfig:
    """Modeled serving policies for one simulated model tier.

    Mirrors the live knobs: ``buckets``/``max_batch`` (padding ladder and
    coalescing bound), ``batch_timeout_ms`` (fill window),
    ``max_queue`` (bounded admission queue), ``service_ms`` (scalar
    per-batch time, or a ``bucket -> ms`` callable from
    :func:`service_ms_from_modeled_cost`), ``breaker_threshold`` /
    ``breaker_open_ms`` (circuit breaker), ``fail_batches`` (injected
    batch failures by global batch ordinal — the chaos analogue), and
    ``fallback`` (a cheaper :class:`SimConfig` absorbing shed/refused
    traffic in degraded mode)."""

    def __init__(self, service_ms, buckets=(1, 4, 16, 64), max_batch=None,
                 batch_timeout_ms=2.0, max_queue=256,
                 breaker_threshold=3, breaker_open_ms=500.0,
                 fail_batches=(), fallback=None):
        self.buckets = tuple(sorted(int(b) for b in set(buckets)))
        self.max_batch = int(max_batch) if max_batch else self.buckets[-1]
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.max_queue = int(max_queue)
        if callable(service_ms):
            self._service = service_ms
        else:
            self._service = lambda bucket, _ms=float(service_ms): _ms
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_open_ms = float(breaker_open_ms)
        self.fail_batches = frozenset(int(b) for b in fail_batches)
        self.fallback = fallback

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def service_ms(self, n):
        return float(self._service(self.bucket_for(n)))

    def est_batch_ms(self):
        """The admission-control scalar (the live batcher's pinned
        ``service_time_hint_ms`` analogue): the max-bucket service
        time."""
        return float(self._service(self.buckets[-1]))


class _SimReq:
    __slots__ = ("t_arrive", "rank", "deadline_ms", "t_deadline", "seq")

    def __init__(self, t, tier, deadline_ms, seq):
        self.t_arrive = t
        self.rank = tier_rank(tier)
        self.deadline_ms = deadline_ms
        self.t_deadline = (t + deadline_ms) if deadline_ms is not None \
            else None
        self.seq = seq

    def key(self):
        return (self.rank,
                self.t_deadline if self.t_deadline is not None
                else float("inf"),
                self.seq)

    @property
    def tier(self):
        return tier_name(self.rank)


class _Replica:
    """One modeled replica: a tier-ordered queue + a single in-flight
    batch slot, the live Batcher's worker discipline on virtual time."""

    __slots__ = ("idx", "cfg", "queue", "busy_until", "window_until",
                 "consecutive_failures", "breaker_open_until", "trips")

    def __init__(self, idx, cfg):
        self.idx = idx
        self.cfg = cfg
        self.queue = []              # sorted by _SimReq.key()
        self.busy_until = None       # t the in-flight batch completes
        self.window_until = None     # coalescing window close
        self.consecutive_failures = 0
        self.breaker_open_until = None
        self.trips = 0

    def load(self):
        return len(self.queue) + (1 if self.busy_until is not None else 0)

    def breaker_open(self, now):
        return self.breaker_open_until is not None \
            and now < self.breaker_open_until

    def modeled_wait_ms(self, position):
        est = self.cfg.est_batch_ms()
        in_flight = 1 if self.busy_until is not None else 0
        return (position // self.cfg.max_batch + 1 + in_flight) * est


class SimReport(dict):
    """Plain dict with the stable keys (documented in docs/mlops.md):
    served/shed/degraded counts, per-tier p50/p99, reqs_per_sec, breaker
    trips, span_ms — everything deterministic for a fixed trace."""

    def render(self):
        lines = ["simulated %d arrivals over %.1fs -> %.1f reqs/sec "
                 "served (%d served, %d shed, %d rejected, %d degraded, "
                 "%d breaker trips)"
                 % (self["arrivals"], self["span_ms"] / 1e3,
                    self["reqs_per_sec"], self["served"],
                    self["shed_total"], self["rejected_total"],
                    self["degraded_total"], self["breaker_trips"])]
        for tier, row in sorted(self["tiers"].items()):
            lines.append("  %-7s n=%-6d p50=%7.2fms p99=%7.2fms shed=%d"
                         % (tier, row["count"], row["p50_ms"],
                            row["p99_ms"], row["shed"]))
        return "\n".join(lines)


class FleetSimulator:
    """Replay a trace against ``replicas`` modeled servers of ``cfg``.

    Arrivals route to the least-loaded replica (deterministic tie-break
    by index — the ordinal dispatch a front-end LB approximates);
    everything after that is the live Batcher's arithmetic on virtual
    time.  ``run()`` returns a :class:`SimReport`; two runs over the
    same trace are byte-identical.
    """

    # event-kind ordering at equal timestamps: finish batches before
    # admitting new arrivals before closing coalescing windows — the
    # tie-break is part of the determinism contract
    _DONE, _ARRIVE, _WINDOW = 0, 1, 2

    def __init__(self, cfg, replicas=1, fallback_replicas=1):
        self.cfg = cfg
        self.replicas = [_Replica(i, cfg) for i in range(int(replicas))]
        self.fallback = None
        if cfg.fallback is not None:
            self.fallback = FleetSimulator(cfg.fallback,
                                           replicas=int(fallback_replicas))

    # -- the admission path (the Batcher's submit(), virtualized) ----------
    def _admit(self, rep, req, now, out):
        position = bisect.bisect_left([r.key() for r in rep.queue],
                                      req.key())
        if req.deadline_ms is not None:
            wait = rep.modeled_wait_ms(position)
            if wait > req.deadline_ms:
                out.shed(req, "admit")
                return False
        if len(rep.queue) >= self.cfg.max_queue:
            if rep.queue and req.key() < rep.queue[-1].key():
                victim = rep.queue.pop()
                out.shed(victim, "evict")
            else:
                out.reject(req)
                return False
        keys = [r.key() for r in rep.queue]
        rep.queue.insert(bisect.bisect_left(keys, req.key()), req)
        return True

    def _sweep(self, rep, now, out):
        keep = []
        for pos, r in enumerate(rep.queue):
            if r.t_deadline is not None and \
                    now + rep.modeled_wait_ms(pos) > r.t_deadline:
                out.shed(r, "sweep")
            else:
                keep.append(r)
        rep.queue = keep

    def run(self, trace, server_free_at_ms=None):
        """Simulate ``trace`` (``[(t_ms, tier, deadline_ms), ...]``) to
        completion; returns the :class:`SimReport`.

        ``server_free_at_ms`` models servers that are busy until a known
        instant (the parked-worker validation scenario: a fully-queued
        backlog released at once) — every replica starts draining then."""
        out = _Collector()
        events = []
        # the third tuple slot is a globally-unique event ordinal: equal
        # (t, kind) events pop in push order and the heap never falls
        # through to comparing payloads
        event_seq = [0]

        def push(t, kind, payload):
            heapq.heappush(events, (t, kind, event_seq[0], payload))
            event_seq[0] += 1

        for seq, (t, tier, deadline) in enumerate(sorted(trace)):
            push(float(t), self._ARRIVE,
                 _SimReq(float(t), tier, deadline, seq))
        if server_free_at_ms is not None:
            for rep in self.replicas:
                rep.busy_until = float(server_free_at_ms)
                push(float(server_free_at_ms), self._DONE, (rep, [], None))
        batch_ordinal = [0]
        degraded = []            # requests rerouted to the fallback

        def start_batch(rep, now):
            self._sweep(rep, now, out)
            if not rep.queue:
                rep.window_until = None
                return
            n = min(len(rep.queue), self.cfg.max_batch)
            batch, rep.queue = rep.queue[:n], rep.queue[n:]
            svc = self.cfg.service_ms(n)
            ordinal = batch_ordinal[0]
            batch_ordinal[0] += 1
            done = now + svc
            rep.busy_until = done
            rep.window_until = None
            push(done, self._DONE, (rep, batch, ordinal))

        while events:
            now, kind, _, payload = heapq.heappop(events)
            if kind == self._ARRIVE:
                req = payload
                live = [r for r in self.replicas
                        if not r.breaker_open(now)]
                if not live:
                    # fleet-wide open breakers: degraded mode or drop
                    (degraded if self.fallback is not None
                     else out.breaker_refused).append(req)
                    out.degraded_total += 1 if self.fallback is not None \
                        else 0
                    continue
                rep = min(live, key=lambda r: (r.load(), r.idx))
                if not self._admit(rep, req, now, out):
                    if self.fallback is not None:
                        degraded.append(req)
                        out.degraded_total += 1
                    continue
                if rep.busy_until is None and rep.window_until is None:
                    if len(rep.queue) >= self.cfg.max_batch:
                        start_batch(rep, now)
                    else:
                        rep.window_until = now + self.cfg.batch_timeout_ms
                        push(rep.window_until, self._WINDOW, rep)
                elif rep.busy_until is None and \
                        len(rep.queue) >= self.cfg.max_batch:
                    start_batch(rep, now)
            elif kind == self._WINDOW:
                rep = payload
                if rep.busy_until is None and rep.window_until is not None \
                        and now >= rep.window_until:
                    start_batch(rep, now)
            else:  # _DONE
                rep, batch, ordinal = payload
                rep.busy_until = None
                failed = ordinal in self.cfg.fail_batches
                if failed:
                    rep.consecutive_failures += 1
                    out.failed.extend(batch)
                    if rep.consecutive_failures >= \
                            self.cfg.breaker_threshold:
                        rep.breaker_open_until = \
                            now + self.cfg.breaker_open_ms
                        rep.trips += 1
                        rep.consecutive_failures = 0
                else:
                    rep.consecutive_failures = 0
                    for r in batch:
                        out.serve(r, now)
                if rep.queue:
                    if len(rep.queue) >= self.cfg.max_batch:
                        start_batch(rep, now)
                    else:
                        rep.window_until = now + self.cfg.batch_timeout_ms
                        push(rep.window_until, self._WINDOW, rep)

        report = out.report(trace,
                            trips=sum(r.trips for r in self.replicas),
                            replicas=len(self.replicas))
        if degraded and self.fallback is not None:
            # degraded-mode slice: replay onto the cheaper variant with
            # original arrival times (deadlines intact)
            sub = self.fallback.run(
                [(r.t_arrive, r.tier, r.deadline_ms) for r in degraded])
            report["fallback"] = sub
        return report


class _Collector:
    def __init__(self):
        self.latency_by_tier = {}
        self.shed_by_tier = {}
        self.shed_by_at = {"admit": 0, "evict": 0, "sweep": 0}
        self.rejected = []
        self.failed = []
        self.breaker_refused = []
        self.degraded_total = 0
        self.served_n = 0
        self.last_done = 0.0

    def serve(self, req, now):
        self.served_n += 1
        self.last_done = max(self.last_done, now)
        self.latency_by_tier.setdefault(req.tier, []).append(
            now - req.t_arrive)

    def shed(self, req, at):
        self.shed_by_tier[req.tier] = self.shed_by_tier.get(req.tier, 0) + 1
        self.shed_by_at[at] += 1

    def reject(self, req):
        self.rejected.append(req)

    def report(self, trace, trips, replicas):
        tiers = {}
        for tier in sorted(set(self.latency_by_tier)
                           | set(self.shed_by_tier)):
            lat = self.latency_by_tier.get(tier, [])
            tiers[tier] = {
                "count": len(lat),
                "p50_ms": round(percentile(lat, 50), 3),
                "p99_ms": round(percentile(lat, 99), 3),
                "shed": self.shed_by_tier.get(tier, 0),
            }
        t0 = min((t for t, _, _ in trace), default=0.0)
        span = max(self.last_done - t0, 1e-9)
        return SimReport(
            arrivals=len(trace),
            served=self.served_n,
            shed_total=sum(self.shed_by_tier.values()),
            shed_at=dict(self.shed_by_at),
            rejected_total=len(self.rejected),
            failed_total=len(self.failed),
            degraded_total=self.degraded_total,
            breaker_refused=len(self.breaker_refused),
            breaker_trips=trips,
            replicas=replicas,
            span_ms=round(span, 3),
            reqs_per_sec=round(self.served_n / (span / 1e3), 3),
            tiers=tiers,
        )


def required_replicas(cfg, trace, slo_tier="gold", slo_p99_ms=None,
                      max_shed_rate=0.0, max_total_shed_rate=0.01,
                      max_replicas=4096, fallback_replicas=1):
    """Smallest replica count whose simulated ``slo_tier`` p99 meets
    ``slo_p99_ms`` with at most ``max_shed_rate`` of that tier shed AND
    at most ``max_total_shed_rate`` of ALL traffic shed/rejected — the
    capacity answer, by exponential probe + binary search (both
    deterministic).  The total-shed bound matters: tier-ordered shedding
    will happily sacrifice bronze to keep gold green, so judging gold
    alone would under-provision the fleet by exactly the overload the
    lowest tier silently absorbs.  Returns ``(replicas, report)``;
    raises when even ``max_replicas`` cannot meet the SLO (the trace is
    beyond this service-time model)."""
    if slo_p99_ms is None:
        raise ValueError("slo_p99_ms is required")

    def meets(k):
        rep = FleetSimulator(cfg, replicas=k,
                             fallback_replicas=fallback_replicas).run(trace)
        row = rep["tiers"].get(slo_tier,
                               {"count": 0, "p99_ms": 0.0, "shed": 0})
        n = row["count"] + row["shed"]
        shed_rate = (row["shed"] / float(n)) if n else 0.0
        dropped = rep["shed_total"] + rep["rejected_total"] \
            + rep["breaker_refused"]
        total_rate = dropped / float(max(1, rep["arrivals"]))
        ok = row["p99_ms"] <= float(slo_p99_ms) \
            and shed_rate <= float(max_shed_rate) \
            and total_rate <= float(max_total_shed_rate)
        return ok, rep

    lo, hi, best = 1, 1, None
    while hi <= int(max_replicas):
        ok, rep = meets(hi)
        if ok:
            best = (hi, rep)
            break
        lo, hi = hi + 1, hi * 2
    if best is None:
        raise ValueError(
            "no replica count <= %d meets %s p99 <= %.1fms for this "
            "trace" % (max_replicas, slo_tier, float(slo_p99_ms)))
    hi = best[0]
    while lo < hi:
        mid = (lo + hi) // 2
        ok, rep = meets(mid)
        if ok:
            hi, best = mid, (mid, rep)
        else:
            lo = mid + 1
    return best
