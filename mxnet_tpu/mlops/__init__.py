"""mxnet_tpu.mlops — the production loop, closed.

Training produces checkpoints; serving hosts fleets; telemetry measures
both.  This package is the control plane that connects them (ROADMAP
item 5, the train/serve ecosystem of the TensorFlow system paper):

- :mod:`.promote` — the **promotion controller**: watches a checkpoint
  directory, ramps each new candidate onto a deterministic canary slice
  of the live fleet's traffic (seeded hash split, pinned fraction
  schedule), judges it from PR-9 registry metrics (tier p99 vs SLO,
  shed rate, breaker state, golden-set output parity vs the incumbent)
  and promotes or rolls back automatically — every decision a versioned
  JSON audit record plus a flight-ring event.  CLI: ``tools/promote.py``.
- :mod:`.simulator` — the **fleet capacity simulator**: a deterministic
  discrete-event replay of seeded millions-of-users traffic (diurnal +
  burst generators) against the *modeled* batcher/tier-shed/breaker/
  degraded-mode policies, with service time from the PR-4 modeled cost,
  validated against the real host serving bench within a documented
  tolerance.  "How many replicas for 1M DAU at gold SLO?" becomes
  :func:`~mxnet_tpu.mlops.simulator.required_replicas` — and
  ``tools/capacity.py``.
- :mod:`.bench` — the host-only bench stage (r05 subprocess pattern)
  emitting ``simulator_accuracy_pct``, ``promotion_decision_ms`` and
  ``capacity_replicas_for_1m_dau``, gated by ``tools/bench_compare.py``.

Everything here is host-only (stdlib + the existing serving/resilience/
telemetry tiers; jax only transitively through runners the caller
builds), deterministic for a fixed seed, and free of wall-clock reads in
the decision path — the SRV005 lint sweeps the package in
``--self-check``.  See docs/mlops.md.
"""
from __future__ import annotations

from .promote import (AUDIT_SCHEMA_VERSION, PromotionController,
                      golden_parity, read_audit_records,
                      runner_from_trainer_checkpoint)
from .simulator import (FleetSimulator, SimConfig, SimReport, burst_trace,
                        diurnal_trace, required_replicas,
                        service_ms_from_modeled_cost, trace_for_dau)

__all__ = [
    "PromotionController", "AUDIT_SCHEMA_VERSION", "golden_parity",
    "read_audit_records", "runner_from_trainer_checkpoint",
    "FleetSimulator", "SimConfig", "SimReport", "burst_trace",
    "diurnal_trace", "trace_for_dau", "required_replicas",
    "service_ms_from_modeled_cost",
]
