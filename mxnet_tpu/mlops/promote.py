"""Promotion controller: train→canary→serve, closed automatically.

The missing wire between three finished subsystems (ROADMAP item 5, the
train/serve ecosystem loop of the TensorFlow system paper, arxiv
1605.08695): PR-6 checkpoints land in a directory, the PR-8 fleet can
hot-swap models under drain, and the PR-9 registry already measures
everything — but a new checkpoint still reached traffic by hand.  This
controller closes the loop:

1. **watch** — :meth:`PromotionController.poll` scans the checkpoint
   directory; a snapshot whose provenance digest differs from the
   incumbent's (and from every digest already judged) becomes the
   *candidate*;
2. **canary** — the candidate is loaded (``runner_factory``), registered
   beside the incumbent and armed as a deterministic traffic split
   (``ModelFleet.set_canary``: seeded hash of the request id, fraction
   ramped along the pinned ``schedule`` — never by wall clock);
3. **judge** — each :meth:`evaluate` tick reads its evidence from the
   PR-9 metrics registry (canary tier p99 vs the declared SLO, canary
   shed rate, breaker state) plus output parity vs the incumbent on a
   pinned *golden request set* (computed, published to the registry,
   then read back like every other metric — the SRV005 lint pins this:
   no wall-clock reads anywhere in the decision path);
4. **decide** — all checks green with enough canary traffic advances the
   ramp; green at the final stage **promotes** (hot swap under drain,
   canary deregistered); any red check **rolls back** (split cleared,
   candidate deregistered, digest remembered so a bad checkpoint is
   never retried).

Every decision writes a versioned JSON audit record
(``audit-<seq>.json``, schema pinned by :data:`AUDIT_SCHEMA_VERSION`)
carrying the decision, the failed metric (if any), both checkpoint
digests and the full evidence — plus a flight-ring event
(``mlops.promotion``) and a registry counter.  The decision-relevant
subset (:meth:`decisions`) is deterministic by construction: the
headline chaos test replays a full train→canary→rollback sequence twice
and byte-compares it.

Chaos probe site: ``mlops.decision`` fires at the top of every evaluate
tick (count = tick ordinal, ctx = (model, state)) so fault schedules can
kill or stall the controller at any decision boundary.
"""
from __future__ import annotations

import json
import os

import numpy as _np

from ..base import MXNetError
from ..resilience import chaos as _chaos
from ..resilience import checkpoint as _ckpt
from ..serving.fleet import DEFAULT_CANARY_SCHEDULE

__all__ = ["PromotionController", "AUDIT_SCHEMA_VERSION", "golden_parity",
           "runner_from_trainer_checkpoint", "read_audit_records"]

# bump when the audit-record layout changes; readers refuse newer
AUDIT_SCHEMA_VERSION = 1

# default pinned golden set size (overridable per controller)
DEFAULT_GOLDEN_N = 32


def golden_parity(incumbent_runner, candidate_runner, golden):
    """Output parity of two runners on the pinned golden request set:
    the fraction of rows whose argmax agrees (multi-output heads), or
    whose values agree within 1e-3 relative (scalar heads).  Pure
    function of the two parameter sets and the golden bytes — the same
    checkpoints always score the same parity."""
    a = _np.asarray(incumbent_runner.forward_batch(golden))
    b = _np.asarray(candidate_runner.forward_batch(golden))
    if a.ndim >= 2 and a.shape[-1] > 1:
        agree = _np.argmax(a, axis=-1) == _np.argmax(b, axis=-1)
    else:
        agree = _np.isclose(a, b, rtol=1e-3, atol=1e-5).reshape(len(a), -1) \
            .all(axis=1)
    return float(_np.mean(agree))


def runner_from_trainer_checkpoint(path_or_record, net_builder,
                                   example_shape, buckets=(1, 4, 16),
                                   dtype="float32", **runner_kwargs):
    """Build a serving :class:`ModelRunner` from a trainer ``.mxckpt``
    snapshot: ``net_builder()`` reconstructs the architecture (a fresh
    hybridizable Gluon block), checkpoint params map onto it positionally
    with shape checks (the trainer's gensym-shift discipline), and the
    checkpoint's provenance rides the runner into fleet ``/stats``.
    Returns ``(runner, provenance_dict)``."""
    from ..serving.runner import ModelRunner

    if isinstance(path_or_record, dict):
        rec = path_or_record
    else:
        rec = _ckpt.load_checkpoint(path_or_record)
    payload = rec["payload"]
    net = net_builder()
    params = net.collect_params()
    names_ckpt = list(payload["params"])
    names_net = list(params.keys())
    if len(names_ckpt) != len(names_net):
        raise MXNetError(
            "checkpoint has %d params, net_builder() built %d — "
            "different architecture" % (len(names_ckpt), len(names_net)))
    for cn, nn in zip(names_ckpt, names_net):
        value = _ckpt.decode_array(payload["params"][cn])
        p = params[nn]
        # deferred dims show as 0: only fully-known shapes are checked
        # (set_data adopts the checkpoint shape into deferred params)
        if p.shape is not None and 0 not in tuple(p.shape) \
                and tuple(p.shape) != tuple(value.shape):
            raise MXNetError(
                "checkpoint param %r %r does not fit net param %r %r"
                % (cn, tuple(value.shape), nn, tuple(p.shape)))
        p.set_data(_np.asarray(value, dtype=p.dtype or value.dtype))
    net.hybridize()
    runner = ModelRunner(net, buckets=buckets, example_shape=example_shape,
                         dtype=dtype, provenance=_ckpt.provenance(rec),
                         **runner_kwargs)
    return runner, _ckpt.provenance(rec)


def read_audit_records(audit_dir):
    """Load every audit record in ``audit_dir`` ascending by seq,
    refusing records written by a newer schema (the parse_log
    discipline)."""
    out = []
    try:
        names = sorted(n for n in os.listdir(audit_dir)
                       if n.startswith("audit-") and n.endswith(".json"))
    except OSError:
        return []
    for name in names:
        with open(os.path.join(audit_dir, name)) as f:
            rec = json.load(f)
        ver = rec.get("schema_version")
        if ver is not None and ver > AUDIT_SCHEMA_VERSION:
            raise ValueError(
                "audit record %s has schema_version %s > supported %d — "
                "refusing to misread a newer controller's trail"
                % (name, ver, AUDIT_SCHEMA_VERSION))
        out.append(rec)
    return out


class PromotionController:
    """Watch a checkpoint directory; canary, judge and promote/rollback
    candidates automatically.  See the module docstring for the state
    machine; ``docs/mlops.md`` documents every knob and the audit
    schema.

    Parameters
    ----------
    fleet : the live :class:`~mxnet_tpu.serving.fleet.ModelFleet`
    model : name of the incumbent entry to ramp candidates against
    checkpoint_dir : directory of ``.mxckpt`` snapshots to watch
    runner_factory : ``(path, record) -> (runner, provenance)`` — how a
        candidate snapshot becomes a servable runner
        (:func:`runner_from_trainer_checkpoint` curried, usually)
    golden : pinned golden request array ``(n,) + example_shape`` for
        the output-parity check (None skips parity)
    audit_dir : where ``audit-<seq>.json`` records land (required)
    schedule / split_seed : the pinned canary ramp + hash seed
    min_stage_requests : canary requests served before a stage is judged
    parity_threshold : golden parity below this fails the candidate
    max_shed_rate : canary shed rate above this fails the candidate
    slo_tier : tier whose canary p99 is judged against the incumbent's
        declared ``tier_slos`` (stages with no declared SLO skip it)
    register_kwargs : forwarded to ``fleet.register`` for the canary
        (service hints, queue depth, ...)
    """

    CANARY_SUFFIX = "__canary"

    def __init__(self, fleet, model, checkpoint_dir, runner_factory,
                 golden=None, audit_dir=None,
                 schedule=DEFAULT_CANARY_SCHEDULE, split_seed=0,
                 min_stage_requests=16, parity_threshold=0.8,
                 max_shed_rate=0.05, slo_tier="gold",
                 register_kwargs=None, registry=None):
        if audit_dir is None:
            raise MXNetError("audit_dir is required: undocumented "
                             "promotion decisions are the failure mode "
                             "this controller exists to end")
        self.fleet = fleet
        self.model = str(model)
        self.checkpoint_dir = str(checkpoint_dir)
        self.runner_factory = runner_factory
        self.golden = None if golden is None else _np.asarray(golden)
        self.audit_dir = str(audit_dir)
        os.makedirs(self.audit_dir, exist_ok=True)
        self.schedule = tuple(schedule)
        self.split_seed = int(split_seed)
        self.min_stage_requests = int(min_stage_requests)
        self.parity_threshold = float(parity_threshold)
        self.max_shed_rate = float(max_shed_rate)
        self.slo_tier = str(slo_tier)
        self.register_kwargs = dict(register_kwargs or {})
        if registry is None:
            from .. import telemetry as _tele
            registry = _tele.registry()
        self.registry = registry
        self.state = "idle"            # idle | canary
        self.candidate = None          # dict while a canary is ramping
        self._judged_digests = set()   # never re-canary a judged digest
        self._seq = len(read_audit_records(self.audit_dir))
        self._ticks = 0
        self._stage_base_requests = 0  # canary requests when stage began
        self._decisions = []

    # -- identity ----------------------------------------------------------
    @property
    def canary_name(self):
        return self.model + self.CANARY_SUFFIX

    def incumbent_digest(self):
        prov = getattr(self.fleet.entry(self.model).runner,
                       "provenance", None)
        return prov.get("digest") if prov else None

    def decisions(self):
        """The deterministic decision sequence: every audit record's
        ``decision`` section, in order — what the headline test
        byte-compares across reruns."""
        return list(self._decisions)

    def decisions_blob(self):
        return json.dumps(self._decisions, sort_keys=True)

    # -- registry access (the SRV005 contract) -----------------------------
    def _scrape(self):
        """One registry scrape -> ``{(name, (label pairs)): value}``.
        EVERY judged number flows through here: promotion evidence is
        registry metrics, never ad-hoc reads."""
        doc = self.registry.to_json(source="mlops.promote")["metrics"]
        out = {}
        for name, entry in doc.items():
            for sample in entry.get("samples", ()):
                labels = tuple(sorted(
                    (str(k), str(v))
                    for k, v in (sample.get("labels") or {}).items()))
                if "value" in sample:
                    out[(name, labels)] = sample["value"]
                elif "p99" in sample:   # histogram cells
                    out[(name + ":p99", labels)] = sample["p99"]
        return out

    @staticmethod
    def _get(scrape, name, **labels):
        """Look up a sample whose labels contain ``labels``."""
        want = set((str(k), str(v)) for k, v in labels.items())
        for (n, lab), value in scrape.items():
            if n == name and want <= set(lab):
                return value
        return None

    # -- watching ----------------------------------------------------------
    def poll(self):
        """Scan the checkpoint directory; start a canary for a fresh
        candidate digest.  Returns the start decision record, or None."""
        if self.state != "idle":
            return None
        found = _ckpt.latest_checkpoint(self.checkpoint_dir)
        if found is None:
            return None
        path, rec = found
        prov = _ckpt.provenance(rec) or {}
        digest = prov.get("digest")
        if digest is None or digest in self._judged_digests:
            return None
        if digest == self.incumbent_digest():
            self._judged_digests.add(digest)
            return None
        runner, prov = self.runner_factory(path, rec)
        self.fleet.register(self.canary_name, runner,
                            **self.register_kwargs)
        split = self.fleet.set_canary(self.model, self.canary_name,
                                      schedule=self.schedule,
                                      seed=self.split_seed)
        self.state = "canary"
        self.candidate = {"digest": digest, "path": path,
                          "provenance": prov, "runner": runner}
        self._stage_base_requests = 0
        return self._audit("start_canary", stage=split.stage,
                           fraction=split.fraction,
                           evidence={"checkpoint": os.path.basename(path)})

    # -- judging -----------------------------------------------------------
    def _evidence(self):
        """Gather the decision evidence from one registry scrape (plus
        the parity gauge this tick published).  Returns (evidence dict,
        failed metric name or None)."""
        canary = self.canary_name
        # golden parity: computed, PUBLISHED to the registry, then read
        # back out of the same scrape every other metric comes from
        if self.golden is not None:
            parity = golden_parity(self.fleet.runner(self.model),
                                   self.candidate["runner"], self.golden)
            self.registry.gauge(
                "mxtpu_canary_golden_parity",
                "output parity candidate vs incumbent on the golden "
                "set").set(parity, model=self.model, canary=canary)
        scrape = self._scrape()
        requests = self._get(scrape, "mxtpu_serving_requests_total",
                             model=canary) or 0
        shed = self._get(scrape, "mxtpu_serving_shed_total",
                         model=canary) or 0
        breaker = self._get(scrape, "mxtpu_serving_breaker_state",
                            model=canary) or 0
        parity_v = self._get(scrape, "mxtpu_canary_golden_parity",
                             model=self.model, canary=canary)
        p99 = self._get(scrape, "mxtpu_serving_tier_p99_ms",
                        model=canary, tier=self.slo_tier)
        slo = self.fleet.entry(self.model).tier_slos.get(self.slo_tier)
        arrived = requests + shed
        shed_rate = (shed / float(arrived)) if arrived else 0.0
        evidence = {
            "canary_requests": int(requests),
            "canary_shed": int(shed),
            "canary_shed_rate": round(shed_rate, 6),
            "breaker_state": int(breaker),
            "golden_parity": None if parity_v is None
            else round(float(parity_v), 6),
            "slo_tier": self.slo_tier,
            "canary_p99_ms": None if p99 is None else float(p99),
            "slo_p99_ms": slo,
        }
        if breaker:
            return evidence, "breaker_state"
        if parity_v is not None and parity_v < self.parity_threshold:
            return evidence, "golden_parity"
        if shed_rate > self.max_shed_rate:
            return evidence, "canary_shed_rate"
        if slo is not None and p99 is not None and p99 > float(slo):
            return evidence, "canary_p99_ms"
        return evidence, None

    def evaluate(self):
        """One decision tick.  Returns the decision record written (or
        None when idle / still gathering evidence)."""
        self._ticks += 1
        _chaos.maybe_inject("mlops.decision", count=self._ticks,
                            ctx=(self.model, self.state))
        if self.state != "canary":
            return None
        split = self.fleet.entry(self.model).canary
        if split is None:   # externally cleared — resync
            self.state = "idle"
            return None
        evidence, failed = self._evidence()
        stage_requests = evidence["canary_requests"] \
            - self._stage_base_requests
        if failed is None and stage_requests < self.min_stage_requests:
            return None     # not enough canary evidence yet: no decision
        if failed is not None:
            return self._rollback(split, evidence, failed)
        if split.final_stage:
            return self._promote(split, evidence)
        self._stage_base_requests = evidence["canary_requests"]
        fraction = self.fleet.advance_canary(self.model)
        return self._audit("advance", stage=split.stage,
                           fraction=fraction, evidence=evidence)

    # -- terminal decisions ------------------------------------------------
    def _promote(self, split, evidence):
        digest = self.candidate["digest"]
        stage, fraction = split.stage, split.fraction
        self.fleet.clear_canary(self.model)
        # hot swap under drain: the candidate runner replaces the
        # incumbent's; queued requests are served by the promoted model,
        # zero in-flight failures (the PR-8 contract)
        self.fleet.swap(self.model, self.candidate["runner"])
        self.fleet.deregister(self.canary_name)
        self._judged_digests.add(digest)
        self.candidate = None
        self.state = "idle"
        return self._audit("promote", stage=stage, fraction=fraction,
                           evidence=evidence, digest=digest)

    def _rollback(self, split, evidence, failed):
        digest = self.candidate["digest"]
        stage, fraction = split.stage, split.fraction
        self.fleet.clear_canary(self.model)
        self.fleet.deregister(self.canary_name)
        self._judged_digests.add(digest)
        self.candidate = None
        self.state = "idle"
        return self._audit("rollback", stage=stage, fraction=fraction,
                           evidence=evidence, failed_metric=failed,
                           digest=digest)

    # -- the audit trail ---------------------------------------------------
    def _audit(self, decision, stage, fraction, evidence,
               failed_metric=None, digest=None):
        self._seq += 1
        dec = {
            "seq": self._seq,
            "model": self.model,
            "decision": decision,
            "stage": int(stage),
            "fraction": float(fraction),
            "candidate_digest": digest if digest is not None
            else (self.candidate or {}).get("digest"),
            "incumbent_digest": self.incumbent_digest(),
            "failed_metric": failed_metric,
        }
        record = {
            "schema_version": AUDIT_SCHEMA_VERSION,
            "decision": dec,
            "evidence": evidence,
        }
        path = os.path.join(self.audit_dir, "audit-%06d.json" % self._seq)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(record, f, sort_keys=True, indent=1)
        os.replace(tmp, path)
        self._decisions.append(dec)
        from .. import telemetry as _tele
        _tele.record("mlops.promotion", **dec)
        self.registry.counter(
            "mxtpu_promotion_decisions_total",
            "promotion controller decisions by kind").inc(
                model=self.model, decision=decision)
        return record

    # -- convenience -------------------------------------------------------
    def run(self, pump=None, max_ticks=200):
        """Poll + evaluate until a terminal decision.  ``pump(tick)`` is
        called before each evaluate while a canary ramps (the caller's
        traffic driver — tests and the demo CLI feed seeded request
        streams through it).  Returns the terminal record, or None when
        ``max_ticks`` ran out."""
        for tick in range(int(max_ticks)):
            self.poll()
            if self.state == "canary" and pump is not None:
                pump(tick)
            rec = self.evaluate()
            if rec and rec["decision"]["decision"] in ("promote",
                                                       "rollback"):
                return rec
        return None
