"""Engine control surface (reference: python/mxnet/engine.py).

The reference exposes ``bulk(size)`` — batching engine ops into segments
(``threaded_engine.h:469`` BulkAppend/BulkFlush) — and internal start/stop.
On TPU, XLA's async dispatch queue plays the engine's role and jit tracing
is the bulking mechanism, so these are semantic no-ops kept for script
parity; ``bulk`` still functions as a hint boundary (it flushes pending
async work on exit, which is the observable behaviour of a bulk segment
boundary in the reference).
"""
from __future__ import annotations

import contextlib

import jax

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 15


def set_bulk_size(size):
    """Reference: MXEngineSetBulkSize; returns the previous size."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """Bulk execution scope (reference: engine.py bulk).  XLA already
    pipelines dispatches; exiting the scope synchronizes like a segment
    flush."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
        try:
            jax.effects_barrier()
        except AttributeError:
            pass
