"""Run-ahead dispatch engine (reference: python/mxnet/engine.py over
``src/engine/threaded_engine.h``).

The reference's asynchronous dependency engine lets the host push operations
without waiting for device completion; ``bulk(size)`` batches them into
segments (``threaded_engine.h:469`` BulkAppend/BulkFlush) so the dispatch
queue stays full.  On TPU, XLA's async dispatch queue plays the worker-pool
role — every jitted call returns immediately with future-backed arrays — but
an *unbounded* run-ahead is as wrong as a synchronous loop: the host can
enqueue arbitrarily many steps, each pinning its input batch and output
buffers in HBM until the device catches up.

This module is therefore the bounding surface the reference's engine had
built in:

- ``set_bulk_size(n)`` — the run-ahead window: a training loop (the
  ``DataParallelTrainer`` in-flight ring) dispatches up to ``n`` steps
  without synchronizing, then applies backpressure by waiting on the
  *oldest* in-flight step.  Dispatch order is untouched, so numerics are
  bitwise-identical at any window size — only synchronization points move.
- ``bulk(size)`` — scopes the window like the reference's bulk segments and
  flushes all in-flight work on exit (the observable behaviour of a segment
  boundary), returning the previous size from the context manager.
- ``flush()`` — the explicit segment flush: drains every registered
  in-flight ring (trainers, prefetchers), then ``jax.effects_barrier()``.

Components with in-flight device work register a flush callback via
``register_flusher`` (held weakly — a dropped trainer unregisters itself).
"""
from __future__ import annotations

import contextlib
import threading
import weakref

import jax

__all__ = ["bulk", "set_bulk_size", "bulk_size", "flush",
           "register_flusher"]

_bulk_size = 15
_lock = threading.Lock()
# weak refs to flush callables of components holding in-flight work
_flushers = []


def set_bulk_size(size):
    """Set the run-ahead window (reference: MXEngineSetBulkSize); returns
    the previous size.  ``1`` keeps at most one step in flight (the
    synchronous loop); larger values let the host run ahead of the device
    by up to ``size`` dispatched-but-unfinished steps."""
    global _bulk_size
    size = int(size)
    if size < 1:
        raise ValueError("bulk size must be >= 1, got %d" % size)
    prev = _bulk_size
    _bulk_size = size
    return prev


def bulk_size():
    """The current run-ahead window."""
    return _bulk_size


def register_flusher(fn):
    """Register a flush callback (held weakly) run by ``flush()``/``bulk``
    exit.  ``fn`` is typically a bound method draining an in-flight ring
    (e.g. ``DataParallelTrainer.flush``)."""
    ref = weakref.WeakMethod(fn) if hasattr(fn, "__self__") \
        else weakref.ref(fn)
    with _lock:
        _flushers.append(ref)


def flush():
    """Wait for ALL in-flight engine work: drain every registered ring,
    then barrier any remaining async effects.  This is the explicit bulk
    segment flush (reference: ThreadedEngine::WaitForAll)."""
    from .resilience import chaos as _chaos
    # chaos probe: a scheduled kill/stall lands exactly at the segment
    # boundary — the "crash mid-bulk-window" case the checkpoint layer
    # must survive (tests/test_resilience.py)
    _chaos.maybe_inject("engine.flush")
    with _lock:
        live = [r() for r in _flushers]
        # compact dropped components in passing
        _flushers[:] = [r for r, f in zip(list(_flushers), live)
                        if f is not None]
        live = [f for f in live if f is not None]
    for fn in live:
        fn()
    jax.effects_barrier()


@contextlib.contextmanager
def bulk(size):
    """Bulk execution scope (reference: engine.py bulk): widen (or narrow)
    the run-ahead window inside the block; exiting restores the previous
    size — which the context manager also yields — and runs an explicit
    ``flush()``, so crossing the boundary synchronizes like a bulk segment
    flush even when the body raised."""
    prev = set_bulk_size(size)
    try:
        yield prev
    finally:
        set_bulk_size(prev)
        flush()
