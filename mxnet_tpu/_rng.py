"""Global RNG state bridging MXNet's seeded-global-RNG model onto jax PRNG keys.

Reference: per-device RNG resources handed to ops via ResourceManager
(``include/mxnet/resource.h:42`` kRandom, ``src/resource.cc``), seeded by
``mx.random.seed``.  jax PRNG is explicit-key; we keep a process-global key
that eager random ops split from, and a *provider stack* so that traced code
(hybridized CachedOp, Symbol executors) draws subkeys deterministically from a
key that is threaded in as a real argument — keeping the trace pure while
every call still sees fresh randomness.
"""
from __future__ import annotations

import threading

import jax
import numpy as np

_state = threading.local()


def _providers():
    if not hasattr(_state, "stack"):
        _state.stack = [EagerKeyProvider(np.random.randint(0, 2**31))]
    return _state.stack


class EagerKeyProvider:
    """Derives keys from numpy state; used outside any trace.

    Only host-side numpy state is stored — with omnistaging, any jax op
    executed while some trace is active yields a tracer, and storing that
    globally (as a split-key chain would) leaks it out of the trace."""

    def __init__(self, seed):
        self.seed(seed)

    def seed(self, seed):
        self._rs = np.random.RandomState(seed)
        self._counter = 0

    def next_key(self):
        # 63-bit seed + a fold-in counter: collision-free in practice
        # (a 31-bit space would birthday-collide within a training run)
        base = int(self._rs.randint(0, 2 ** 63, dtype=np.int64))
        self._counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(base), self._counter)


class TraceKeyProvider:
    """Derives subkeys from a (possibly traced) base key with a fold counter.

    Pushed while tracing a CachedOp / Symbol executor so that random ops
    become pure functions of the key argument.
    """

    def __init__(self, base_key):
        self._base = base_key
        self._n = 0

    def next_key(self):
        self._n += 1
        return jax.random.fold_in(self._base, self._n)

    @property
    def used(self):
        return self._n > 0


def next_key():
    return _providers()[-1].next_key()


def seed(seed_val):
    """mx.random.seed equivalent (reference: python/mxnet/random.py)."""
    _providers()[0].seed(int(seed_val))
    np.random.seed(int(seed_val))


def get_state():
    """Snapshot the eager provider's full state (numpy RandomState tuple +
    fold-in counter) — what a training checkpoint records so a resumed
    run draws the exact same key sequence (resilience/checkpoint.py)."""
    p = _providers()[0]
    return {"numpy_state": p._rs.get_state(), "counter": p._counter}


def set_state(state):
    """Restore a :func:`get_state` snapshot (the resume half)."""
    p = _providers()[0]
    p._rs.set_state(state["numpy_state"])
    p._counter = int(state["counter"])


def push_provider(p):
    _providers().append(p)


def pop_provider():
    return _providers().pop()


class trace_scope:
    def __init__(self, base_key):
        self.provider = TraceKeyProvider(base_key)

    def __enter__(self):
        push_provider(self.provider)
        return self.provider

    def __exit__(self, *exc):
        pop_provider()
