"""Multi-process decode/augment pipeline with shared-memory transport.

Reference: the C++ ``ImageRecordIter`` escapes Python entirely —
``preprocess_threads`` OMP workers decode into pinned buffers and a
prefetcher thread double-buffers the copy (``src/io/
iter_image_recordio_2.cc``, ``iter_prefetcher.h``).  The Python port's
thread pool shares one GIL, so on a small host the chip starves: BENCH_r05
measured the device step at 2391 img/s/chip against a 127 img/s host feed.

This module is the process-parallel analogue:

- **workers** are real processes (forkserver — fork() from a threaded jax
  parent can deadlock, see gluon/data/dataloader.py).  Each worker owns its
  own RecordIO handle and decodes/augments whole batches in numpy; jax is
  never touched in a worker (``ImageIter.next_numpy``), so no worker can
  initialise a device backend.
- **transport** is a pickle-free shared-memory ring: one ``SharedMemory``
  block sliced into per-worker slot sets.  A worker writes the decoded
  batch straight into its slot and sends only ``(epoch, batch, slot, pad)``
  through a queue; the consumer copies the batch out, frees the slot and
  reorders by batch index.  Depth is bounded at ``prefetch_buffer`` slots
  per worker — a slow consumer stops dispatching tasks, which stops the
  workers (backpressure), it never grows memory.
- **determinism**: batches are assigned round-robin (batch ``b`` belongs to
  worker ``b % W``) and the augmentation RNG is seeded per *batch index*,
  not per worker — so the emitted stream is bitwise-identical for any
  worker count, including the in-process ``num_workers=0`` path (which
  runs the exact same decode function inline).
- **failure**: a crashed worker is detected by liveness polling, respawned,
  and its undelivered batches are re-dispatched in order — nothing is
  dropped or duplicated (the reorder buffer is keyed by batch index).
  Platforms without ``multiprocessing.shared_memory`` degrade to the
  in-process path with a one-time warning.
"""
from __future__ import annotations

import collections
import logging
import multiprocessing as _mp
import os
import queue as _queue
import random as _random
import time as _time
import warnings

import numpy as _np

from ..base import MXNetError
from ..resilience import chaos as _chaos
from . import DataBatch, DataIter

__all__ = ["ImagePipelineIter", "PipelineWorkerStorm", "pipeline_available",
           "seed_for_batch"]

_RESPAWN_LIMIT = 3          # default per-worker per-epoch crash budget
_POLL_S = 0.25              # consumer liveness-poll interval
_WORKER_POLL_S = 1.0        # worker-side bounded-blocking poll interval


class PipelineWorkerStorm(MXNetError):
    """A worker died more than ``max_respawns`` times within one epoch.

    A deterministic crasher (corrupt record that segfaults the decoder,
    OOM at a fixed batch) would otherwise respawn-loop forever — the
    respawn budget turns the loop into a clear, immediate error naming
    the worker and its crash count (docs/io.md failure semantics)."""


def pipeline_available():
    """True when the multi-process transport can run on this platform."""
    try:
        from multiprocessing import shared_memory  # noqa: F401
    except ImportError:
        return False
    try:
        _mp.get_context("forkserver")
    except ValueError:
        try:
            _mp.get_context("spawn")
        except ValueError:
            return False
    return True


def _mp_context():
    try:
        return _mp.get_context("forkserver")
    except ValueError:
        return _mp.get_context("spawn")


def seed_for_batch(seed, epoch, batch_idx):
    """The per-batch RNG seed — a function of the *batch index*, never the
    worker, so any process (or the in-process path) produces the same
    augmentation stream for the same batch."""
    return (seed * 1_000_003 + epoch * 8191 + batch_idx) % (1 << 32)


def _seed_rngs(seed, epoch, batch_idx):
    if seed is None:
        return
    s = seed_for_batch(seed, epoch, batch_idx)
    _random.seed(s)
    _np.random.seed(s)


def _attach_shm(name):
    """Attach to an existing SharedMemory block WITHOUT registering it with
    this process's resource tracker: the parent is the sole owner/unlinker,
    and a second registration makes the tracker double-unlink at exit."""
    from multiprocessing import resource_tracker, shared_memory
    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **k: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


class _SlotLayout:
    """Byte layout of one ring slot: data block then label block, both at
    full batch capacity (partial tail batches use a row-count header in the
    queue message, not the buffer)."""

    def __init__(self, data_shape, data_dtype, label_shape):
        self.data_shape = tuple(data_shape)
        self.data_dtype = _np.dtype(data_dtype)
        self.label_shape = tuple(label_shape)
        self.data_bytes = int(_np.prod(self.data_shape)) * \
            self.data_dtype.itemsize
        self.label_bytes = int(_np.prod(self.label_shape)) * 4
        self.slot_bytes = self.data_bytes + self.label_bytes

    def views(self, buf, slot):
        """(data, label) numpy views over slot ``slot`` of ``buf``."""
        base = slot * self.slot_bytes
        data = _np.ndarray(self.data_shape, self.data_dtype,
                           buffer=buf, offset=base)
        label = _np.ndarray(self.label_shape, _np.float32,
                            buffer=buf, offset=base + self.data_bytes)
        return data, label


def _worker_main(wid, shm_name, layout, iter_kwargs, aug_list, seed,
                 task_q, free_q, ready_q):
    """Worker process body: pull (epoch, batch_idx, keys) tasks, decode the
    batch in numpy, write it into a free shared-memory slot, announce it.

    ``ready_q`` is this worker's OWN announce queue (single writer): a
    worker killed mid-``put`` dies holding only its own queue's write lock,
    which the parent discards at respawn — a shared queue would be poisoned
    for every surviving worker.

    Runs no jax: the decode core is ``ImageIter.next_numpy`` and the output
    leaves through shared memory, so the worker can never acquire a device
    backend (critical when the parent holds a TPU).

    Every blocking wait is bounded (the SRC005 discipline): the task and
    slot waits poll at ``_WORKER_POLL_S`` and re-check that the parent is
    still alive — an orphaned worker (parent SIGKILLed) exits instead of
    blocking on a queue nobody will ever feed again."""
    parent = os.getppid()
    shm = _attach_shm(shm_name)
    try:
        from ..image import ImageIter
        it = ImageIter(aug_list=list(aug_list), shuffle=False, **iter_kwargs)
        while True:
            try:
                task = task_q.get(timeout=_WORKER_POLL_S)
            except _queue.Empty:
                if os.getppid() != parent:
                    return          # orphaned: the parent died
                continue
            if task is None:
                break
            epoch, batch_idx, keys = task
            while True:             # backpressure: bounded slots
                try:
                    slot = free_q.get(timeout=_WORKER_POLL_S)
                    break
                except _queue.Empty:
                    if os.getppid() != parent:
                        return
            t0 = _time.perf_counter()
            try:
                _seed_rngs(seed, epoch, batch_idx)
                it.seq = list(keys)
                it.cur = 0
                data, label, pad = it.next_numpy()
                dview, lview = layout.views(shm.buf, slot)
                n = data.shape[0]
                dview[:n] = data
                lview[:n] = label
                busy = _time.perf_counter() - t0
                ready_q.put(("batch", epoch, batch_idx, wid, slot, n, pad,
                             busy))
            except BaseException as e:   # surface decode errors, keep going
                free_q.put(slot)
                ready_q.put(("error", epoch, batch_idx, wid,
                             "%s: %s" % (type(e).__name__, e)))
    finally:
        shm.close()


class ImagePipelineIter(DataIter):
    """Image iterator backed by the multi-process shared-memory pipeline.

    Takes the same kwargs as :class:`~mxnet_tpu.image.ImageIter` plus:

    num_workers : int — decode/augment processes.  0 runs the identical
        decode path inline (the fallback, and the equivalence baseline).
    prefetch_buffer : int — shared-memory slots *per worker* (ring depth);
        also bounds how many undelivered batches a worker may own.
    seed : int or None — deterministic per-batch RNG seeding.  With a seed
        the output stream is bitwise-identical for ANY ``num_workers``;
        ``None`` leaves worker RNGs free-running (fastest shuffle of
        entropy, no reproducibility).
    max_respawns : int — crash budget per worker *per epoch* (default 3);
        exceeding it raises :class:`PipelineWorkerStorm` instead of
        respawn-looping forever on a deterministic crasher.
    """

    def __init__(self, num_workers=None, prefetch_buffer=2, seed=None,
                 max_respawns=_RESPAWN_LIMIT, **kwargs):
        from .. import profiler as _profiler
        from ..image import ImageIter
        if num_workers is None:
            num_workers = min(4, os.cpu_count() or 1)
        self._requested_workers = int(num_workers)
        self._depth = max(1, int(prefetch_buffer))
        self._seed = seed
        self._shuffle = bool(kwargs.pop("shuffle", False))
        self._epoch = 0

        # template: builds the record index + augmenter chain once, serves
        # as the in-process decoder, and donates its auglist to workers so
        # order-randomised chains (ColorJitterAug shuffles at construction)
        # are identical everywhere
        self._template = ImageIter(shuffle=False, **kwargs)
        super().__init__(self._template.batch_size)
        self._base_seq = list(self._template.seq)
        if not self._base_seq:
            raise MXNetError("pipeline needs a keyed record source "
                             "(path_imgrec with an index, or an imglist)")
        self._iter_kwargs = dict(kwargs)
        self._iter_kwargs.pop("aug_list", None)
        self._aug_list = self._template.auglist
        self._last_batch_handle = self._template.last_batch_handle

        self._n_workers = self._requested_workers
        if self._n_workers > 0 and not pipeline_available():
            warnings.warn(
                "multiprocessing shared memory unavailable on this "
                "platform; ImagePipelineIter falls back to in-process "
                "decoding", RuntimeWarning)
            self._n_workers = 0

        d = self._template.provide_data[0]
        lw = self._template.label_width
        self._layout = _SlotLayout(d.shape, d.dtype, (self.batch_size, lw))
        self.stats = _profiler.PipelineStats(self._n_workers)

        self._shm = None
        self._procs = []
        self._task_qs = []
        self._free_qs = []
        self._ready_qs = []
        self._respawns = 0
        self._max_respawns = int(max_respawns)
        # per-worker per-epoch crash counts (the storm budget's unit)
        self._worker_respawns = [0] * max(1, self._n_workers)
        if self._n_workers > 0:
            self._start_workers()
        self._begin_epoch()

    # -- process management ------------------------------------------------
    def _start_workers(self):
        from multiprocessing import shared_memory
        ctx = _mp_context()
        self._ctx = ctx
        n_slots = self._n_workers * self._depth
        self._shm = shared_memory.SharedMemory(
            create=True, size=n_slots * self._layout.slot_bytes)
        self._ready_qs = []             # one per worker: single writer
        self._slot_owner = {}           # slot -> worker id
        for w in range(self._n_workers):
            self._task_qs.append(None)
            self._free_qs.append(None)
            self._ready_qs.append(None)
            self._procs.append(None)
            self._spawn_worker(w)

    def _spawn_worker(self, wid):
        """(Re)create worker ``wid`` with fresh queues and all of its slots
        free.  Used at startup and after a crash — the caller re-dispatches
        any undelivered batches.  Queues are never reused across a worker
        generation: a SIGKILLed worker may die holding its ready queue's
        write lock or with a half-written pickle in the pipe, either of
        which would wedge a reader forever."""
        ctx = self._ctx
        task_q = ctx.Queue()
        free_q = ctx.Queue()
        ready_q = ctx.Queue()
        for s in range(wid * self._depth, (wid + 1) * self._depth):
            free_q.put(s)
            self._slot_owner[s] = wid
        proc = ctx.Process(
            target=_worker_main,
            args=(wid, self._shm.name, self._layout, self._iter_kwargs,
                  self._aug_list, self._seed, task_q, free_q, ready_q),
            daemon=True)
        proc.start()
        self._task_qs[wid] = task_q
        self._free_qs[wid] = free_q
        self._ready_qs[wid] = ready_q
        self._procs[wid] = proc

    def _discard_queues(self, wid):
        for qs in (self._task_qs, self._free_qs, self._ready_qs):
            q = qs[wid]
            if q is not None:
                q.cancel_join_thread()
                q.close()
                qs[wid] = None

    # -- epoch plumbing ----------------------------------------------------
    def _begin_epoch(self):
        order = list(self._base_seq)
        if self._shuffle:
            rng = _np.random.RandomState(
                None if self._seed is None else
                (self._seed + self._epoch) % (1 << 32))
            order = [order[i] for i in rng.permutation(len(order))]
        b = self.batch_size
        batches = [order[i:i + b] for i in range(0, len(order), b)]
        if batches and len(batches[-1]) < b and \
                self._last_batch_handle == "discard":
            batches.pop()
        self._batches = batches
        self._next_out = 0               # next batch index to emit
        self._done = {}                  # batch_idx -> (data, label, pad)
        self._in_flight = [collections.deque()
                           for _ in range(max(1, self._n_workers))]
        # strict round-robin ownership: worker w owns batches w, w+W, ...
        # — each worker's batch-index stream is monotonic, which is what
        # makes the slot ring deadlock-free (docs/io.md)
        self._next_for_worker = list(range(max(1, self._n_workers)))
        self._exhausted = not batches
        # a fresh epoch resets the crash budget: the storm bound is
        # "max_respawns per worker per epoch"
        self._worker_respawns = [0] * max(1, self._n_workers)
        self.stats.on_epoch()
        if self._n_workers > 0:
            self._fill_dispatch()

    def _fill_dispatch(self):
        """Top up every worker to at most ``depth`` undelivered batches —
        the task side of the backpressure bound (a slow consumer stops
        calling this, which idles the workers)."""
        for wid in range(self._n_workers):
            while self._next_for_worker[wid] < len(self._batches) and \
                    len(self._in_flight[wid]) < self._depth:
                self._dispatch(wid, self._next_for_worker[wid])
                self._next_for_worker[wid] += self._n_workers

    def _dispatch(self, wid, batch_idx):
        # chaos probe: a scheduled fault SIGKILLs a worker (action "call"
        # through ctx) or delays dispatch at a chosen batch index
        _chaos.maybe_inject("pipeline.dispatch", ctx=(self, wid, batch_idx))
        keys = self._batches[batch_idx]
        self._in_flight[wid].append((self._epoch, batch_idx))
        self._task_qs[wid].put((self._epoch, batch_idx, keys))

    # -- consumption -------------------------------------------------------
    def _pump(self, block=True):
        """Drain whatever the workers have announced into the reorder
        buffer.  Blocks (bounded) on the ready pipes via connection.wait;
        every timeout polls worker liveness and recovers crashes.  Returns
        True when at least one message was consumed."""
        got = False
        for wid in range(self._n_workers):
            q = self._ready_qs[wid]
            while q is not None:
                try:
                    msg = q.get_nowait()
                except _queue.Empty:
                    break
                self._handle_msg(msg)
                got = True
        if got or not block:
            return got
        import multiprocessing.connection as _conn
        readers = [q._reader for q in self._ready_qs if q is not None]
        _conn.wait(readers, timeout=_POLL_S)
        if not any(r.poll() for r in readers):
            self._check_workers()
        return False

    def _handle_msg(self, msg):
        if msg[0] == "error":
            _, epoch, batch_idx, wid, text = msg
            if epoch != self._epoch:
                return
            self._forget_in_flight(wid, batch_idx)
            raise MXNetError("pipeline worker %d failed on batch %d: %s"
                             % (wid, batch_idx, text))
        _, epoch, batch_idx, wid, slot, n, pad, busy = msg
        data_v, label_v = self._layout.views(self._shm.buf, slot)
        if epoch == self._epoch:
            # copy out so the slot can recycle immediately; the reorder
            # buffer is bounded by the dispatch throttle (<= W*depth)
            self._done[batch_idx] = (data_v[:n].copy(), label_v[:n].copy(),
                                     pad)
            self._forget_in_flight(wid, batch_idx)
            self.stats.on_batch(wid, busy, len(self._done))
        # stale-epoch deliveries (reset() mid-epoch) just recycle the slot
        owner = self._slot_owner[slot]
        if self._free_qs[owner] is not None:
            self._free_qs[owner].put(slot)

    def _forget_in_flight(self, wid, batch_idx):
        try:
            self._in_flight[wid].remove((self._epoch, batch_idx))
        except ValueError:
            pass

    def _check_workers(self):
        for wid, proc in enumerate(self._procs):
            if proc is None or proc.is_alive():
                continue
            self._recover_worker(wid, proc)

    def _recover_worker(self, wid, proc):
        """Respawn a dead worker and re-dispatch its undelivered batches —
        exactly-once delivery: anything it DID deliver sits in the reorder
        buffer keyed by batch index, anything it did not is re-sent.  The
        dead worker's queues are dropped wholesale (see _spawn_worker), so
        deliveries it completed but the parent had not yet pumped are
        simply re-decoded — wasted work, never a duplicate, because the
        reorder buffer keys on batch index."""
        self._respawns += 1
        self._worker_respawns[wid] += 1
        self.stats.on_respawn()
        if self._worker_respawns[wid] > self._max_respawns:
            raise PipelineWorkerStorm(
                "pipeline worker %d died %d times this epoch (exitcode "
                "%s), exceeding max_respawns=%d — a deterministic "
                "crasher (corrupt record / repeatable OOM), not a "
                "transient fault; inspect the record at the failing "
                "batch instead of respawn-looping"
                % (wid, self._worker_respawns[wid], proc.exitcode,
                   self._max_respawns))
        logging.getLogger(__name__).warning(
            "pipeline worker %d died (exitcode %s); respawning and "
            "requeueing %d batches", wid, proc.exitcode,
            len(self._in_flight[wid]))
        lost = [(e, b) for (e, b) in self._in_flight[wid]
                if e == self._epoch and b not in self._done]
        self._in_flight[wid].clear()
        self._discard_queues(wid)
        self._spawn_worker(wid)
        for e, b in lost:
            self._in_flight[wid].append((e, b))
            self._task_qs[wid].put((e, b, self._batches[b]))

    # -- DataIter API ------------------------------------------------------
    @property
    def provide_data(self):
        return self._template.provide_data

    @property
    def provide_label(self):
        return self._template.provide_label

    def next(self):
        if self._exhausted or self._next_out >= len(self._batches):
            self._exhausted = True
            raise StopIteration
        want = self._next_out
        if self._n_workers == 0:
            _seed_rngs(self._seed, self._epoch, want)
            self._template.seq = list(self._batches[want])
            self._template.cur = 0
            data, label, pad = self._template.next_numpy()
        else:
            if not self._procs:
                raise MXNetError("pipeline is closed")
            t0 = _time.perf_counter()
            while want not in self._done:
                self._pump()
            self.stats.on_wait(_time.perf_counter() - t0)
            data, label, pad = self._done.pop(want)
            self._fill_dispatch()
        self._next_out += 1
        from .. import ndarray as nd
        lw = self._template.label_width
        d = nd.array(data, dtype=data.dtype)
        lab = nd.array(label if lw > 1 else label[:, 0])
        return DataBatch([d], [lab], pad=pad)

    def iter_next(self):
        raise NotImplementedError("use next()")

    def reset(self):
        self._epoch += 1
        if self._n_workers > 0:
            # stale tasks still queued for workers execute and are dropped
            # by epoch tag on delivery (bounded: <= depth per worker);
            # rebuilding processes every epoch would cost seconds
            while self._pump(block=False):
                pass
            self._done.clear()
        self._begin_epoch()

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        procs, self._procs = self._procs, []
        for p in procs:
            if p is not None and p.is_alive():
                p.terminate()
        for p in procs:
            if p is not None:
                p.join(timeout=5)
        for q in self._task_qs + self._free_qs + \
                getattr(self, "_ready_qs", []):
            if q is not None:
                q.cancel_join_thread()
                q.close()
        self._task_qs, self._free_qs, self._ready_qs = [], [], []
        if self._shm is not None:
            try:
                self._shm.close()
                self._shm.unlink()
            except (FileNotFoundError, OSError):
                pass
            self._shm = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
