"""Fused on-device pipeline tail: normalize + cast + layout in one program.

The reference normalizes on the host (``mean_r/std_r`` inside the C++
augmenter chain, ``image_aug_default.cc``) because its device copy is a
plain memcpy.  On TPU the economics invert: shipping the batch as raw
uint8 NHWC makes the host→HBM transfer 4× narrower and leaves zero float
math on the host; the mean/std subtract, dtype cast and layout transpose
then fuse into the device program (XLA fuses them into the first conv's
prologue when traced inside the training step).

Every distinct ``(mean, std, dtype, layout)`` tail is built ONCE and
cached module-wide, so two iterators with the same normalization share one
jitted callable — a stable jit identity is what makes the tail provably
recompile-free (`tail_cache_sizes()` exposes per-tail trace counts the
same way Executor/Module ``jit_cache_keys()`` does for the step program).
"""
from __future__ import annotations

import threading

import numpy as _np

__all__ = ["make_device_tail", "tail_cache_keys", "tail_cache_sizes",
           "clear_tail_cache"]

_CACHE = {}
_LOCK = threading.Lock()


def _key(mean, std, dtype, layout, input_layout):
    def tup(v):
        if v is None:
            return None
        return tuple(float(x) for x in _np.asarray(v).reshape(-1))
    return (tup(mean), tup(std), str(dtype), str(layout), str(input_layout))


def make_device_tail(mean=None, std=None, dtype="float32", layout="NHWC",
                     input_layout="NHWC"):
    """Build (or fetch) the jitted tail ``uint8[B,H,W,C] -> dtype[batch]``.

    mean, std : per-channel (or scalar) normalization constants, applied in
        float32 before the cast so bf16 targets round once, not twice.
    dtype : output dtype (``bfloat16`` for the mixed-precision trainer).
    layout : output layout; ``NCHW`` adds the transpose on device.
    input_layout : layout the host ships (``NHWC`` — the decoder's own).

    The returned callable is a ``jax.jit`` function: applied eagerly (e.g.
    by ``DeviceFeedIter``) it compiles once per input shape; traced inside
    a larger jit (``DataParallelTrainer(input_transform=...)``) it inlines
    into that program, adding no dispatch of its own.
    """
    key = _key(mean, std, dtype, layout, input_layout)
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            return fn
    import jax
    import jax.numpy as jnp
    mean_c = None if mean is None else jnp.asarray(
        _np.asarray(mean, _np.float32))
    std_c = None if std is None else jnp.asarray(
        _np.asarray(std, _np.float32))

    def tail(x):
        y = x.astype(jnp.float32)
        if mean_c is not None:
            y = y - mean_c
        if std_c is not None:
            y = y / std_c
        y = y.astype(dtype)
        if layout == "NCHW" and input_layout == "NHWC":
            y = jnp.transpose(y, (0, 3, 1, 2))
        elif layout == "NHWC" and input_layout == "NCHW":
            y = jnp.transpose(y, (0, 2, 3, 1))
        return y

    fn = jax.jit(tail)
    fn.tail_key = key
    with _LOCK:
        # a racing builder may have landed first; keep the canonical one
        fn = _CACHE.setdefault(key, fn)
    return fn


def tail_cache_keys():
    """The set of distinct tail configurations built so far."""
    with _LOCK:
        return set(_CACHE)


def tail_cache_sizes():
    """{tail key: number of XLA traces}.  Steady-state feeding must hold
    every count at 1 per input geometry — the zero-recompile proof the
    serving layer makes for the step program (PR-2 ``jit_cache_keys``)."""
    out = {}
    with _LOCK:
        items = list(_CACHE.items())
    for key, fn in items:
        try:
            out[key] = int(fn._cache_size())
        except AttributeError:
            out[key] = -1
    return out


def clear_tail_cache():
    with _LOCK:
        _CACHE.clear()
