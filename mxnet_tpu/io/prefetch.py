"""Device prefetch for training loops: ship batch *k+1* while step *k*
executes.

``PrefetchToDeviceIter`` is the training-side specialization of
``DeviceFeedIter`` (reference: the PrefetcherIter thread + pinned-memory
staging in ``src/io/iter_prefetcher.h:47``; on TPU the "pinned buffer" is
a bounded ring of already-sharded device batches):

- batches are ``jax.device_put`` **onto the trainer's batch sharding** on
  the background thread, so ``DataParallelTrainer.step``'s fast path
  reuses the prefetched arrays instead of re-putting them (the transfer
  happens exactly once, overlapped with the previous step's compute);
- the slot ring bounds prefetch HBM to ``depth × batch_bytes`` —
  ``hbm_bound_bytes()`` reports the modeled cap from the batch
  descriptors (the same per-array byte accounting the mxcost transfer
  model uses), so a capacity plan can budget it next to the model's
  ``peak_hbm_bytes``.

Used directly or implicitly through ``DataParallelTrainer.fit``.
"""
from __future__ import annotations

import numpy as _np

from . import DeviceFeedIter

__all__ = ["PrefetchToDeviceIter"]


class PrefetchToDeviceIter(DeviceFeedIter):
    """Prefetch host batches onto ``sharding`` with a ``depth``-slot ring.

    Parameters
    ----------
    base : DataIter yielding host batches.
    sharding : jax.sharding.Sharding, optional — target layout for data
        AND labels (a trainer's ``batch_sharding``); None keeps the
        default device placement.
    depth : int — ring slots; prefetch HBM is capped at
        ``depth × batch_bytes``.
    transform / data_desc : as ``DeviceFeedIter`` (a fused device tail
        composes with the sharded put).
    """

    def __init__(self, base, sharding=None, depth=2, transform=None,
                 data_desc=None):
        super().__init__(base, transform=transform, depth=depth,
                         data_desc=data_desc, sharding=sharding)

    def batch_bytes(self):
        """Bytes one prefetched batch keeps resident (data + labels),
        from the provide_data/provide_label descriptors — the same
        aval-bytes accounting ``analysis.cost`` uses for transfer
        classification (h2d bytes per step == this number)."""
        total = 0
        for desc in list(self.provide_data) + list(self.provide_label or []):
            n = 1
            for d in desc.shape:
                n *= int(d)
            dtype = getattr(desc, "dtype", _np.float32)
            try:
                itemsize = _np.dtype(dtype).itemsize
            except TypeError:  # e.g. the string "bfloat16"
                itemsize = 2 if "16" in str(dtype) else 4
            total += n * itemsize
        return total

    def hbm_bound_bytes(self):
        """The prefetch ring's HBM cap: ``depth × batch_bytes`` — the most
        device memory this iterator will ever pin, by construction of the
        slot semaphore (asserted by ``tests/test_engine.py``)."""
        return self.depth * self.batch_bytes()
