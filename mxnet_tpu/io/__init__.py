"""Data iterators: the `mx.io` namespace.

Reference: ``python/mxnet/io.py`` (DataIter ``:182``, DataBatch ``:118``,
NDArrayIter ``:546``, MXDataIter ``:766`` wrapping the 8 C++ iterators
registered in ``src/io/*.cc``).

TPU-native design: iterators are plain Python producing host numpy batches;
``jax`` overlaps the host→HBM transfer with compute via async dispatch (the
reference needed a dedicated PrefetcherIter thread + pinned memory for the
same overlap).  A thread-backed ``PrefetchingIter`` is still provided for
expensive decode pipelines (the dmlc::ThreadedIter analogue).
"""
from __future__ import annotations

import collections
import queue as _queue
import threading

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array as _nd_array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "DeviceFeedIter", "PrefetchToDeviceIter",
           "CSVIter", "MNISTIter",
           "ImageRecordIter", "ImagePipelineIter", "PipelineWorkerStorm",
           "make_device_tail", "LibSVMIter", "ImageDetRecordIter"]


def ImageRecordIter(**kwargs):
    """Name-parity wrapper over the image pipeline (the C++ registered
    iterator `ImageRecordIter`, src/io/iter_image_recordio_2.cc).

    The C iterator kwargs map onto the TPU-native pipeline:

    - ``preprocess_threads`` — number of decode/augment *worker processes*
      (io/pipeline.py; the reference's OMP decode team).  0 keeps decoding
      in-process behind a prefetch thread.
    - ``prefetch_buffer`` — pipeline ring depth (shared-memory slots per
      worker), or the prefetch-thread queue depth when in-process.
    - ``mean_r/g/b``, ``std_r/g/b`` — normalization constants.
    - ``device_tail=True`` — ship raw uint8 NHWC batches and fuse the
      mean/std normalize + dtype cast + layout transform on device
      (io/device_tail.py); the returned iterator then yields
      device-resident, already-normalized batches.
    - ``seed`` — deterministic per-batch augmentation (bitwise-identical
      output for any worker count).
    """
    from .device_tail import make_device_tail as _make_tail
    from .pipeline import ImagePipelineIter, pipeline_available
    import numpy as _np2
    mean = None
    if any(k in kwargs for k in ("mean_r", "mean_g", "mean_b")):
        mean = _np2.array([kwargs.pop("mean_r", 0.0),
                           kwargs.pop("mean_g", 0.0),
                           kwargs.pop("mean_b", 0.0)], dtype=_np2.float32)
    std = None
    if any(k in kwargs for k in ("std_r", "std_g", "std_b")):
        std = _np2.array([kwargs.pop("std_r", 1.0),
                          kwargs.pop("std_g", 1.0),
                          kwargs.pop("std_b", 1.0)], dtype=_np2.float32)
    mean = kwargs.pop("mean", mean)
    std = kwargs.pop("std", std)
    prefetch = max(1, int(kwargs.pop("prefetch_buffer", 2)))
    workers = int(kwargs.pop("preprocess_threads", 0))
    device_tail = bool(kwargs.pop("device_tail", False))
    seed = kwargs.pop("seed", None)
    # C++ round_batch: True wraps/pads the tail batch, False emits it partial
    if kwargs.pop("round_batch", True):
        kwargs.setdefault("last_batch_handle", "pad")
    else:
        kwargs.setdefault("last_batch_handle", "keep")

    out_dtype = kwargs.get("dtype", "float32")
    out_layout = kwargs.get("layout", "NCHW")
    if device_tail:
        # the host ships what the decoder produces — uint8 NHWC — and the
        # normalize/cast/layout tail runs fused on device
        kwargs["dtype"] = "uint8"
        kwargs["layout"] = "NHWC"
        host_mean = host_std = None
    else:
        host_mean, host_std = mean, std

    if workers > 0 and not pipeline_available():
        _warn_once(
            "ImageRecordIter: multiprocessing shared memory is "
            "unavailable on this platform; preprocess_threads=%d "
            "falls back to in-process decoding" % workers)
        workers = 0
    if workers > 0 or seed is not None:
        # seeded runs go through the pipeline even in-process: its
        # per-batch RNG discipline is what makes the output reproducible
        # (and identical under any worker count)
        inner = ImagePipelineIter(num_workers=workers,
                                  prefetch_buffer=prefetch, seed=seed,
                                  mean=host_mean, std=host_std, **kwargs)
    else:
        from ..image import ImageIter
        inner = PrefetchingIter(
            ImageIter(mean=host_mean, std=host_std, **kwargs),
            depth=prefetch)
    if not device_tail:
        return inner
    tail = _make_tail(mean, std, dtype=out_dtype, layout=out_layout,
                      input_layout="NHWC")
    d = inner.provide_data[0]
    bsz, h, w, c = d.shape
    shape = (bsz, c, h, w) if out_layout == "NCHW" else (bsz, h, w, c)
    desc = [DataDesc(d.name, shape, _np.dtype(out_dtype)
                     if out_dtype != "bfloat16" else out_dtype,
                     layout=out_layout)]
    return DeviceFeedIter(inner, transform=tail, data_desc=desc)


_WARNED = set()


def _warn_once(msg):
    if msg not in _WARNED:
        _WARNED.add(msg)
        import warnings
        warnings.warn(msg, RuntimeWarning, stacklevel=3)


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Name/shape/dtype/layout of one input (reference: io.py:DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: lists of data/label arrays plus bookkeeping
    (reference: io.py:118)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __repr__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        return "DataBatch: data shapes %s" % (shapes,)


class DataIter:
    """Iterator base (reference: io.py:182)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize data/label argument into list of (name, numpy) pairs."""
    if data is None:
        return []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError("empty data")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("cannot interpret data: %r" % type(data))
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:546).  Supports
    shuffle, pad/discard/roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 shuffle_seed=None,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        if shuffle:
            rng = _np.random.RandomState(shuffle_seed)
            idx = rng.permutation(self.num_data)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.cursor = -1

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor >= self.num_batches:
            self.cursor = -1 - (self.num_batches * self.batch_size - self.num_data)
        else:
            self.cursor = -1

    def iter_next(self):
        self.cursor += 1
        return self.cursor < self.num_batches

    def _take(self, arrays):
        start = self.cursor * self.batch_size
        out = []
        for _, v in arrays:
            chunk = v[start:start + self.batch_size]
            if chunk.shape[0] < self.batch_size:
                # pad by wrapping (reference pads from the beginning)
                pad = self.batch_size - chunk.shape[0]
                chunk = _np.concatenate([chunk, v[:pad]], axis=0)
            out.append(_nd_array(chunk, dtype=chunk.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = (self.cursor + 1) * self.batch_size
        if self.last_batch_handle == "pad" and end > self.num_data:
            return end - self.num_data
        return 0

    def getindex(self):
        start = self.cursor * self.batch_size
        return _np.arange(start, start + self.batch_size) % self.num_data


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (reference: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Producer-thread prefetch over one or more iterators (reference:
    io.py PrefetchingIter / src/io/iter_prefetcher.h:47)."""

    def __init__(self, iters, rename_data=None, rename_label=None, depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue = _queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = None
        self._exhausted = False
        self._start()

    def _start(self):
        self._error = None

        def run():
            while not self._stop.is_set():
                try:
                    batches = [it.next() for it in self.iters]
                except StopIteration:
                    self._queue.put(None)
                    return
                except BaseException as e:  # surface at next(), don't hang
                    self._error = e
                    self._queue.put(None)
                    return
                self._queue.put(batches)
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for it in self.iters:
            it.reset()
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._queue.maxsize)
        self._exhausted = False
        self._start()

    def next(self):
        if self._exhausted:
            raise StopIteration
        batches = self._queue.get()
        if batches is None:
            self._exhausted = True
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            raise StopIteration
        b = batches[0]
        if len(batches) > 1:
            data = sum([list(x.data) for x in batches], [])
            label = sum([list(x.label or []) for x in batches], [])
            return DataBatch(data, label or None, pad=b.pad, index=b.index)
        return b

    def iter_next(self):
        raise NotImplementedError("use next()")


class DeviceFeedIter(DataIter):
    """Double-buffered device feed (reference: ``iter_prefetcher.h:47`` +
    the per-executor copy in ``executor_group.py _load_data``).

    A worker thread pulls host batches from ``base``, moves them to device
    (optionally through a jitted ``transform``, optionally onto an explicit
    ``sharding``) and **synchronizes the transfer before handing the batch
    over**.  Two effects: the device always holds the next batch when the
    trainer asks for it, and — on remote-tunnel transports where a long
    h2d RPC and compute dispatch RPCs contend pathologically when
    interleaved — the tunnel runs one big transfer at a time while the
    previous step's compute proceeds on device.

    ``depth`` is a hard slot ring: at most ``depth`` prefetched batches
    are device-resident at once (queued *or* mid-transfer — a slot
    semaphore gates the worker before it touches the next batch), so
    prefetch HBM is capped at ``depth × batch_bytes``.  Feed/stall
    accounting lands in ``self.stats`` (``profiler.PipelineStats``).
    """

    def __init__(self, base, transform=None, depth=2, data_desc=None,
                 sharding=None):
        super().__init__(base.batch_size)
        import jax as _jax

        from ..profiler import PipelineStats
        self._jax = _jax
        self.base = base
        self.transform = transform
        self.sharding = sharding
        # post-transform data descriptors: a device-side tail changes the
        # batch's dtype/layout, so consumers binding from provide_data must
        # see the transformed geometry, not the host one
        self._data_desc = data_desc
        self._depth = max(1, int(depth))
        self.stats = PipelineStats(num_workers=1, name="io.device_feed")
        # observability for the HBM bound: the most slots ever live at once
        self._live = 0
        self._live_max = 0
        self._live_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._exhausted = False
        # serializes base-iterator access across worker generations: a
        # worker stuck in a long transfer past reset()'s join timeout must
        # not interleave base.next() with its replacement
        self._base_lock = threading.Lock()
        self._make_ring()
        self._start()

    def _make_ring(self):
        # +1: the end-of-epoch sentinel must never block behind a full
        # ring of real batches (slots gate those, not the sentinel)
        self._queue = _queue.Queue(maxsize=self._depth + 1)
        self._slots = threading.Semaphore(self._depth)

    @property
    def depth(self):
        return self._depth

    @property
    def live_slots_max(self):
        """Most prefetched batches simultaneously device-resident so far
        (must never exceed ``depth`` — the HBM bound the tests assert)."""
        with self._live_lock:
            return self._live_max

    @property
    def provide_data(self):
        if self._data_desc is not None:
            return self._data_desc
        return self.base.provide_data

    @property
    def provide_label(self):
        return self.base.provide_label

    def _to_device(self, batch):
        from ..ndarray import NDArray

        def put(arr, transform):
            raw = arr._data if isinstance(arr, NDArray) else \
                self._jax.numpy.asarray(arr)
            if transform is not None:
                raw = transform(raw)
            if self.sharding is not None:
                raw = self._jax.device_put(raw, self.sharding)
            return raw

        # the transform (a fused device tail) applies to the DATA only;
        # labels ride along untouched
        outs = [put(a, self.transform) for a in batch.data]
        labels = [put(a, None) for a in (batch.label or [])]
        # fence the transfer inside the worker: the consumer must never
        # block on (or contend with) a half-shipped batch
        self._jax.block_until_ready(outs + labels)
        return DataBatch([NDArray(o) for o in outs],
                         [NDArray(l) for l in labels] or None,
                         pad=batch.pad, index=batch.index)

    def _start(self):
        # the worker captures ITS OWN stop event, queue, slot ring and
        # error box: after a timed-out reset() swaps in fresh ones, a
        # zombie worker can neither pollute the new queue, nor miss its
        # (already set) stop signal, nor write a stale exception into the
        # new epoch
        import time as _time
        self._error_box = err = [None]
        stop, q, slots = self._stop, self._queue, self._slots

        def run():
            while not stop.is_set():
                # the slot gates BEFORE the batch is pulled/transferred:
                # acquire fails until the consumer frees a slot, so at
                # most `depth` batches are ever device-resident
                if not slots.acquire(timeout=0.2):
                    continue
                try:
                    with self._base_lock:
                        if stop.is_set():
                            return
                        host_batch = self.base.next()
                    with self._live_lock:
                        self._live += 1
                        self._live_max = max(self._live_max, self._live)
                    t0 = _time.perf_counter()
                    b = self._to_device(host_batch)
                    self.stats.on_batch(0, _time.perf_counter() - t0,
                                        q.qsize() + 1)
                except StopIteration:
                    q.put(None)
                    return
                except BaseException as e:
                    err[0] = e
                    q.put(None)
                    return
                q.put(b)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def reset(self):
        import time as _time
        self._stop.set()
        # drain while joining: the worker may be blocked on a full queue,
        # and its final put must not deadlock the join
        deadline = _time.monotonic() + 10
        while self._thread is not None and self._thread.is_alive():
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=0.25)
            if _time.monotonic() > deadline:
                # stuck mid-transfer: abandon it — its captured queue/event
                # are about to be swapped out and the base lock keeps it
                # from touching the iterator again
                break
        with self._base_lock:
            self.base.reset()
        self._stop = threading.Event()
        self._make_ring()
        with self._live_lock:
            self._live = 0
        self._exhausted = False
        self._start()

    def next(self):
        import time as _time
        if self._exhausted:
            raise StopIteration
        t0 = _time.perf_counter()
        b = self._queue.get()
        self.stats.on_wait(_time.perf_counter() - t0)
        if b is None:
            self._exhausted = True
            if self._error_box[0] is not None:
                err, self._error_box[0] = self._error_box[0], None
                raise err
            raise StopIteration
        # batch handed over: its ring slot frees and the worker may pull
        # (and start transferring) the next host batch
        with self._live_lock:
            self._live -= 1
        self._slots.release()
        return b

    def iter_next(self):
        raise NotImplementedError("use next()")


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", ndmin=2, dtype=_np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", ndmin=2,
                                dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        else:
            label = _np.zeros((data.shape[0],), dtype=_np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="roll_over" if round_batch else "pad")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """LibSVM text-format iterator yielding CSR batches
    (reference: src/io/iter_libsvm.cc — "label idx:val idx:val ..." lines,
    zero-based indices; labels from a separate file when ``label_libsvm``
    is given, else the leading value per line).

    data comes out as CSRNDArray (batch_size, *data_shape) — the sparse
    storage the row-sparse linear models train on."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=(1,), batch_size=1, num_parts=1, part_index=0,
                 round_batch=True, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self._data_shape = tuple(data_shape)
        self._feat_dim = 1
        for d in self._data_shape:
            self._feat_dim *= d
        rows, inline_labels = self._parse(data_libsvm, with_label=True)
        if label_libsvm is not None:
            lab_rows, _ = self._parse(label_libsvm, with_label=False)
            labels = _np.asarray([r[1][0] if len(r[1]) else 0.0
                                  for r in lab_rows], _np.float32)
        else:
            labels = _np.asarray(inline_labels, _np.float32)
        # worker sharding, as the reference's num_parts/part_index
        if num_parts > 1:
            n_per = len(rows) // num_parts
            rows = rows[part_index * n_per:(part_index + 1) * n_per]
            labels = labels[part_index * n_per:(part_index + 1) * n_per]
        self._rows = rows
        self._labels = labels
        self._round_batch = round_batch
        self._cursor = 0
        self.data_name = data_name
        self.label_name = label_name
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self._data_shape)]
        self.provide_label = [DataDesc(label_name, (batch_size,))]

    @staticmethod
    def _parse(path, with_label):
        rows, labels = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                start = 0
                if with_label:
                    labels.append(float(parts[0]))
                    start = 1
                idx, val = [], []
                for tok in parts[start:]:
                    i, v = tok.split(":")
                    idx.append(int(i))
                    val.append(float(v))
                rows.append((_np.asarray(idx, _np.int64),
                             _np.asarray(val, _np.float32)))
        return rows, labels

    def reset(self):
        self._cursor = 0

    def next(self):
        from ..ndarray import sparse
        if self._cursor >= len(self._rows):
            raise StopIteration
        take = self._rows[self._cursor:self._cursor + self.batch_size]
        labs = self._labels[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        pad = self.batch_size - len(take)
        if pad and self._round_batch:
            take = list(take) + [self._rows[-1]] * pad
            labs = _np.concatenate([labs,
                                    _np.repeat(labs[-1:], pad)])
        else:
            pad = 0
        indptr = _np.zeros(len(take) + 1, _np.int64)
        cols, vals = [], []
        for i, (idx, val) in enumerate(take):
            cols.append(idx)
            vals.append(val)
            indptr[i + 1] = indptr[i] + len(idx)
        cols = _np.concatenate(cols) if cols else _np.zeros(0, _np.int64)
        vals = _np.concatenate(vals) if vals else _np.zeros(0, _np.float32)
        data = sparse.CSRNDArray(
            _nd_array(vals), _nd_array(cols, dtype="int64"),
            _nd_array(indptr, dtype="int64"),
            (len(take), self._feat_dim))
        return DataBatch([data], [_nd_array(labs)], pad=pad)


def ImageDetRecordIter(**kwargs):
    """Detection record iterator (reference: src/io/
    iter_image_det_recordio.cc).  Name-parity wrapper over
    image.ImageDetIter with the C kwargs mapped (mean_r/g/b etc.)."""
    from ..image.detection import ImageDetIter
    mean = None
    if any(k in kwargs for k in ("mean_r", "mean_g", "mean_b")):
        mean = _np.array([kwargs.pop("mean_r", 0.0),
                          kwargs.pop("mean_g", 0.0),
                          kwargs.pop("mean_b", 0.0)], dtype=_np.float32)
    std = None
    if any(k in kwargs for k in ("std_r", "std_g", "std_b")):
        std = _np.array([kwargs.pop("std_r", 1.0),
                         kwargs.pop("std_g", 1.0),
                         kwargs.pop("std_b", 1.0)], dtype=_np.float32)
    threads = kwargs.pop("preprocess_threads", None)
    if threads:
        # the detection pipeline decodes in-process (boxes ride the labels
        # through augmenters the worker pool does not ship yet); say so
        # once instead of silently eating the knob
        _warn_once(
            "ImageDetRecordIter: preprocess_threads=%s is not yet wired "
            "to the multi-process pipeline for detection records; "
            "decoding runs in-process (prefetch_buffer is honored)"
            % threads)
    prefetch = max(1, int(kwargs.pop("prefetch_buffer", 2)))
    if kwargs.pop("round_batch", True):
        kwargs.setdefault("last_batch_handle", "pad")
    else:
        kwargs.setdefault("last_batch_handle", "keep")
    return PrefetchingIter(ImageDetIter(mean=mean, std=std, **kwargs),
                           depth=prefetch)


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 seed=0, silent=False, input_shape=None, **kwargs):
        super().__init__(batch_size)
        import gzip
        import struct

        def _open(path):
            return gzip.open(path, "rb") if str(path).endswith(".gz") else \
                open(path, "rb")

        with _open(image) as f:
            magic, n, h, w = struct.unpack(">IIII", f.read(16))
            imgs = _np.frombuffer(f.read(), dtype=_np.uint8).reshape(n, h, w)
        with _open(label) as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            labs = _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.float32)
        imgs = imgs.astype(_np.float32) / 255.0
        if flat or (input_shape and len(input_shape) == 1):
            imgs = imgs.reshape(n, h * w)
        else:
            imgs = imgs.reshape(n, 1, h, w)
        self._inner = NDArrayIter(imgs, labs, batch_size, shuffle=shuffle,
                                  shuffle_seed=seed,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


# imported at the tail: these modules consume the DataIter/DataBatch/DataDesc
# definitions above (mxnet_tpu.io is already in sys.modules by then)
from .device_tail import make_device_tail  # noqa: E402
from .pipeline import (ImagePipelineIter, PipelineWorkerStorm,  # noqa: E402,F401
                       pipeline_available)
from .prefetch import PrefetchToDeviceIter  # noqa: E402
