"""Host-only input-pipeline micro-bench: ``python -m mxnet_tpu.io.bench``.

Measures what the host can FEED, with no accelerator in the loop (run as a
``JAX_PLATFORMS=cpu`` subprocess by bench.py, the PR-2 serving pattern —
the number stays live even when the TPU backend is down, which is exactly
when BENCH_r03..r05 starved every pipeline key).

``fed`` here means: decode + augment + transfer fenced on the (cpu)
device + the fused normalization tail applied, per batch, measured over a
steady-state epoch (workers up, jits warm — construction/compile cost is
paid in a warm-up epoch, as in steady training).  Three variants:

- legacy: the in-process float path — host-side mean/std normalize,
  float32 NCHW batches (what the port did before the pipeline PR);
- new: the multi-process shared-memory pipeline shipping raw uint8 NHWC
  with the device-side fused tail (``device_tail=True``);
- a worker-scaling curve for the new pipeline (0 = in-process), from
  which the headline ``pipeline_fed_imgs_per_sec`` takes the best
  config on this host (reported in ``pipeline_best_workers``).

Prints one JSON line; bench.py merges it into the round record.
"""
from __future__ import annotations

import io as _pyio
import json
import os
import shutil
import sys
import tempfile
import time


def _synth_rec(n, size=224):
    import numpy as np
    from PIL import Image

    from .. import recordio
    tmpdir = tempfile.mkdtemp(prefix="mxtpu_pipe_bench_")
    rec = os.path.join(tmpdir, "synth.rec")
    idx = os.path.join(tmpdir, "synth.idx")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    buf = _pyio.BytesIO()
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        buf.seek(0)
        buf.truncate()
        Image.fromarray(img).save(buf, format="JPEG", quality=90)
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0), buf.getvalue()))
    w.close()
    return tmpdir, rec, idx


def _timed_epoch(make_iter, consume):
    """Steady-state epoch rate: epoch 1 warms (workers, prefetch, jit
    compiles), epoch 2 is timed."""
    it = make_iter()
    n_img = 0
    for b in it:
        consume(b)
    it.reset()
    t0 = time.perf_counter()
    for b in it:
        consume(b)
        n_img += b.data[0].shape[0]
    dt = time.perf_counter() - t0
    # prefer the decode pipeline's stats (worker pool utilization) over
    # the DeviceFeedIter wrapper's own feed-thread stats
    if hasattr(getattr(it, "base", None), "stats"):
        stats = it.base.stats.snapshot()
    elif hasattr(it, "stats"):
        stats = it.stats.snapshot()
    else:
        stats = None
    close = getattr(it, "close", None) or getattr(
        getattr(it, "base", None), "close", None)
    if close:
        close()
    return n_img / dt, stats


def main():
    import jax
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native, recordio

    n = int(os.environ.get("MXTPU_PIPE_BENCH_N", "768"))
    batch = int(os.environ.get("MXTPU_PIPE_BENCH_BATCH", "128"))
    size = int(os.environ.get("MXTPU_PIPE_BENCH_SIZE", "224"))
    workers_curve = [int(w) for w in os.environ.get(
        "MXTPU_PIPE_BENCH_WORKERS", "0,1,2").split(",")]
    tmpdir, rec, idx = _synth_rec(n, size)
    out = {"pipeline_host_cores": os.cpu_count(),
           "pipeline_batch": batch, "pipeline_n_records": n}
    try:
        # raw native decode rate: the host's physical ceiling
        if _native.available():
            r = recordio.MXIndexedRecordIO(idx, rec, "r")
            bufs = [recordio.unpack(r.read_idx(i))[1] for i in range(n)]
            r.close()
            t0 = time.perf_counter()
            _native.decode_batch(bufs, size, size, 3)
            out["pipeline_decode_imgs_per_sec"] = round(
                n / (time.perf_counter() - t0), 2)
            del bufs

        mean = dict(mean_r=123.68, mean_g=116.28, mean_b=103.53,
                    std_r=58.395, std_g=57.12, std_b=57.375)
        # the consumer: one tiny jitted reduction per batch, fenced — a
        # stand-in for "the device accepted this batch" that costs the
        # same for every variant
        consumed = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

        def consume(b):
            consumed(b.data[0]._data).block_until_ready()

        # legacy: in-process float path, host normalize, NCHW float32
        def legacy():
            return mx.io.DeviceFeedIter(mx.io.ImageRecordIter(
                path_imgrec=rec, path_imgidx=idx, batch_size=batch,
                data_shape=(3, size, size), shuffle=False, **mean))
        rate, _ = _timed_epoch(legacy, consume)
        out["pipeline_fed_legacy_imgs_per_sec"] = round(rate, 2)

        # new: uint8 NHWC + fused device tail, over the worker curve
        scaling = {}
        best, best_w, best_stats = 0.0, 0, None
        for w in workers_curve:
            def new_pipe(w=w):
                return mx.io.ImageRecordIter(
                    path_imgrec=rec, path_imgidx=idx, batch_size=batch,
                    data_shape=(3, size, size), shuffle=False,
                    layout="NHWC", device_tail=True, seed=0,
                    preprocess_threads=w, prefetch_buffer=2, **mean)
            rate, stats = _timed_epoch(new_pipe, consume)
            scaling[str(w)] = round(rate, 2)
            if rate > best:
                best, best_w, best_stats = rate, w, stats
        out["pipeline_worker_scaling"] = scaling
        out["pipeline_fed_imgs_per_sec"] = round(best, 2)
        out["pipeline_best_workers"] = best_w
        if out.get("pipeline_fed_legacy_imgs_per_sec"):
            out["pipeline_speedup_vs_legacy"] = round(
                best / out["pipeline_fed_legacy_imgs_per_sec"], 2)
        if best_stats:
            out["pipeline_stall_pct"] = best_stats["stall_pct"]
            out["pipeline_worker_utilization"] = \
                best_stats["worker_utilization"]
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
