"""Indexing ops: Embedding/take/gather/scatter/one_hot/pick/where.

Reference: ``src/operator/tensor/indexing_op.{h,cc,cu}``.  These are
gather/scatter lowered to XLA; the Embedding op's backward (scatter-add of
output grads into the weight) is what the reference implements with
AddTakeGrad CUDA kernels — jax.vjp of jnp.take generates the same
scatter-add for us.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("Embedding", arg_names=["data", "weight"])
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """Integer-index lookup into a (input_dim, output_dim) weight table
    (reference: src/operator/tensor/indexing_op.cc Embedding)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("take", arg_names=["a", "indices"])
def take(a, indices, axis=0, mode="clip"):
    """Select slices of data along `axis` by integer indices with clip/wrap
    modes (reference: src/operator/tensor/indexing_op.cc take)."""
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", arg_names=["a", "indices"])
def batch_take(a, indices):
    """Per-row element selection: out[i] = a[i, indices[i]] (reference:
    src/operator/tensor/indexing_op.cc batch_take)."""
    return jnp.take_along_axis(a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("pick", arg_names=["data", "index"])
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    """Pick one element per row along `axis` by integer index (reference:
    src/operator/tensor/broadcast_reduce_op_index.cc pick)."""
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    idxe = jnp.expand_dims(idx, axis if axis >= 0 else data.ndim + axis)
    out = jnp.take_along_axis(data, idxe, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("one_hot", differentiable=False)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    """Expand integer indices into one-hot vectors of `depth` (reference:
    src/operator/tensor/indexing_op.cc one_hot)."""
    from ..base import np_dtype
    oh = jax.nn.one_hot(indices.astype(jnp.int32), int(depth))
    out = oh * (on_value - off_value) + off_value
    return out.astype(np_dtype(dtype))


@register("gather_nd", arg_names=["data", "indices"])
def gather_nd(data, indices):
    """Gather slices addressed by leading index tuples (reference:
    src/operator/tensor/indexing_op.cc gather_nd)."""
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd", arg_names=["data", "indices"])
def scatter_nd(data, indices, shape=()):
    """Scatter values into a zeros tensor of `shape` by index tuples
    (reference: src/operator/tensor/indexing_op.cc scatter_nd)."""
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register("_scatter_set_nd", arg_names=["lhs", "rhs", "indices"])
def scatter_set_nd(lhs, rhs, indices, shape=()):
    """Indexed assignment kernel behind NDArray.__setitem__ (reference:
    src/operator/tensor/indexing_op.cc scatter_set_nd)."""
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


@register("where", arg_names=["condition", "x", "y"])
def where(condition, x, y):
    """Elementwise select from x/y by condition (reference:
    src/operator/tensor/control_flow_op.cc where)."""
    return jnp.where(condition.astype(bool), x, y)


def _seq_len_optional(params):
    """sequence_length input only exists when use_sequence_length=True
    (reference: src/operator/sequence_last-inl.h param)."""
    if params.get("use_sequence_length", False):
        return ()
    return ("sequence_length",)


@register("SequenceMask", arg_names=["data", "sequence_length"],
          optional_args=_seq_len_optional)
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    """Reference: src/operator/sequence_mask.cc — data is (seq, batch, ...) for axis=0."""
    if not use_sequence_length or sequence_length is None:
        return data
    seq_len = data.shape[axis]
    pos = jnp.arange(seq_len)
    mask = pos[:, None] < sequence_length[None, :].astype(jnp.int32)  # (seq, batch)
    if axis == 1:
        mask = mask.T
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast", arg_names=["data", "sequence_length"],
          optional_args=_seq_len_optional)
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    """Select the last valid step of a (seq, batch, ...) tensor per
    sequence_length (reference: src/operator/sequence_last.cc)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, -1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)  # (batch,)
    if axis == 0:
        return jnp.take_along_axis(
            data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
        )[0]
    return jnp.take_along_axis(
        data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1
    )[:, 0]


@register("SequenceReverse", arg_names=["data", "sequence_length"],
          optional_args=_seq_len_optional)
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    """Reverse the time axis up to sequence_length per batch element
    (reference: src/operator/sequence_reverse.cc)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    seq_len = data.shape[0]
    pos = jnp.arange(seq_len)[:, None]
    sl = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(pos < sl, sl - 1 - pos, pos)  # (seq, batch)
    return jnp.take_along_axis(
        data, src.reshape(src.shape + (1,) * (data.ndim - 2)), axis=0
    )


@register("sparse_retain", arg_names=["data", "indices"])
def sparse_retain_dense(data, indices):
    """Keep only the selected rows of a matrix, zeroing the rest (reference:
    src/operator/tensor/sparse_retain.cc)."""
    mask = jnp.zeros((data.shape[0],), dtype=bool).at[indices.astype(jnp.int32)].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)
