"""Fused multi-layer RNN op (RNN/LSTM/GRU) via lax.scan.

Reference: the ``RNN`` operator, ``src/operator/rnn-inl.h:49`` — modes
rnn_relu/rnn_tanh/lstm/gru, multi-layer, bidirectional, cuDNN-packed flat
parameter vector (native impl ``src/operator/rnn_impl.h``, cuDNN path
``src/operator/nn/cudnn/cudnn_rnn-inl.h``).

TPU-native design: the input projection for *all timesteps* of a layer is
one large matmul (MXU-friendly, (T*B, in) @ (in, G*H)); only the recurrent
h2h product lives inside ``lax.scan``.  XLA unrolls nothing — the scan
compiles to a fori loop with static shapes.  Parameter layout matches the
reference's cuDNN packing (all weights layer-major, then all biases) so
checkpoints interop.

Gate orders (cuDNN): LSTM i,f,g,o; GRU r,z,n.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _dirs(bidirectional):
    return 2 if bidirectional else 1


def rnn_param_size(state_size, input_size, num_layers, mode, bidirectional):
    """Total flat parameter count (reference: rnn-inl.h GetParamSize)."""
    g = _GATES[mode]
    d = _dirs(bidirectional)
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * d
        # per direction: W (g*H x in), R (g*H x H), bW (g*H), bR (g*H)
        size += d * (g * state_size * (in_size + state_size) + 2 * g * state_size)
    return size


def rnn_state_shape(attrs, dshape):
    from . import registry as _reg
    num_layers = int(_reg.canonicalize(attrs.get("num_layers", 1)))
    state_size = int(_reg.canonicalize(attrs.get("state_size")))
    d = _dirs(_reg.canonicalize(attrs.get("bidirectional", False)))
    return (num_layers * d, dshape[1], state_size)


def _unpack(params, state_size, input_size, num_layers, mode, bidirectional):
    """Slice the flat vector into per-layer/direction (W, R, bW, bR)."""
    g = _GATES[mode]
    d = _dirs(bidirectional)
    H = state_size
    weights = []
    off = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else H * d
        layer_w = []
        for direction in range(d):
            W = params[off:off + g * H * in_size].reshape(g * H, in_size)
            off += g * H * in_size
            R = params[off:off + g * H * H].reshape(g * H, H)
            off += g * H * H
            layer_w.append([W, R])
        weights.append(layer_w)
    for layer in range(num_layers):
        for direction in range(d):
            bW = params[off:off + g * H]
            off += g * H
            bR = params[off:off + g * H]
            off += g * H
            weights[layer][direction] += [bW, bR]
    return weights


def _cell_step(mode, H, clip_min=None, clip_max=None, clip_nan=False):
    """Return scan body fn(carry, x_proj) for one direction."""
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

        def step(carry, xp, R, bR):
            (h,) = carry
            h_new = act(xp + h @ R.T + bR)
            return (h_new,), h_new
        return step

    if mode == "lstm":
        def step(carry, xp, R, bR):
            h, c = carry
            gates = xp + h @ R.T + bR
            i, f, gg, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            gg = jnp.tanh(gg)
            o = jax.nn.sigmoid(o)
            c_new = f * c + i * gg
            # per-timestep cell clip (reference: rnn-inl.h / cuDNN
            # CUDNN_RNN_CLIP_MINMAX — applied inside the recurrence)
            if clip_nan:
                c_new = jnp.nan_to_num(c_new)
            if clip_min is not None:
                c_new = jnp.clip(c_new, clip_min, clip_max)
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        return step

    if mode == "gru":
        def step(carry, x_and_rproj, R, bR):
            # GRU needs the recurrent product *before* gate mixing for n
            (h,) = carry
            xp = x_and_rproj
            hp = h @ R.T + bR
            xr, xz, xn = jnp.split(xp, 3, axis=-1)
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new
        return step

    raise ValueError("unknown RNN mode %r" % mode)


def _run_direction(x, h0, c0, W, R, bW, bR, mode, reverse,
                   clip_min=None, clip_max=None, clip_nan=False):
    """x: (T, B, in).  Returns (out (T,B,H), h_T, c_T|None)."""
    H = h0.shape[-1]
    if reverse:
        x = jnp.flip(x, axis=0)
    T, B, _ = x.shape
    # one big MXU matmul for every timestep's input projection
    xp = (x.reshape(T * B, -1) @ W.T + bW).reshape(T, B, -1)
    step = _cell_step(mode, H, clip_min, clip_max, clip_nan)

    if mode == "lstm":
        def body(carry, xt):
            return step(carry, xt, R, bR)
        (h_t, c_t), outs = lax.scan(body, (h0, c0), xp)
    else:
        def body(carry, xt):
            return step(carry, xt, R, bR)
        (h_t,), outs = lax.scan(body, (h0,), xp)
        c_t = None
    if reverse:
        outs = jnp.flip(outs, axis=0)
    return outs, h_t, c_t


def _rnn_num_outputs(params):
    from . import registry as _reg
    if not _reg.canonicalize(params.get("state_outputs", False)):
        return 1
    return 3 if params.get("mode", "lstm") == "lstm" else 2


def _rnn_optional(params):
    """state_cell input only exists for LSTM mode."""
    if params.get("mode", "lstm") == "lstm":
        return ()
    return ("state_cell",)


@register("RNN", arg_names=["data", "parameters", "state", "state_cell"],
          num_outputs=_rnn_num_outputs, needs_train=True,
          optional_args=_rnn_optional)
def rnn(data, parameters, state, state_cell=None, state_size=0, num_layers=1,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, projection_size=None, _train=False):
    """Fused RNN forward (reference: src/operator/rnn-inl.h:49).

    data: (seq_len, batch, input_size); state: (L*D, batch, H);
    returns output (seq_len, batch, D*H) [+ final h [+ final c]]."""
    if projection_size:
        raise NotImplementedError(
            "projected LSTM (projection_size) is not supported; the flat "
            "parameter layout would be misread — failing loudly instead")
    state_size = int(state_size)
    num_layers = int(num_layers)
    d = _dirs(bidirectional)
    T, B, input_size = data.shape
    weights = _unpack(parameters, state_size, input_size, num_layers, mode,
                      bidirectional)

    x = data
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        outs_dir = []
        for direction in range(d):
            W, R, bW, bR = weights[layer][direction]
            idx = layer * d + direction
            h0 = state[idx]
            c0 = state_cell[idx] if (mode == "lstm" and state_cell is not None) \
                else jnp.zeros_like(h0)
            out, h_t, c_t = _run_direction(
                x, h0, c0, W, R, bW, bR, mode, reverse=(direction == 1),
                clip_min=lstm_state_clip_min, clip_max=lstm_state_clip_max,
                clip_nan=lstm_state_clip_nan)
            outs_dir.append(out)
            h_finals.append(h_t)
            if mode == "lstm":
                c_finals.append(c_t)
        x = outs_dir[0] if d == 1 else jnp.concatenate(outs_dir, axis=-1)
        if p > 0 and _train and layer + 1 < num_layers:
            # inter-layer dropout (reference: rnn-inl.h dropout between
            # layers); key drawn from the provider so each step/batch gets a
            # fresh mask and traced callers stay pure (see _rng.py)
            from .. import _rng
            keep = jax.random.bernoulli(_rng.next_key(), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0)

    if not state_outputs:
        return x
    h_out = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        c_out = jnp.stack(c_finals, axis=0)
        return x, h_out, c_out
    return x, h_out
