"""Creation ops (reference: ``src/operator/tensor/init_op.cc``)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import np_dtype
from .registry import register


@register("_zeros", arg_names=[], differentiable=False)
def zeros(shape=(), dtype="float32", ctx=None):
    """Zeros-filled tensor of `shape` (reference:
    src/operator/tensor/init_op.cc zeros)."""
    return jnp.zeros(shape, dtype=np_dtype(dtype or "float32"))


@register("_state_zeros_like", arg_names=["ref"], differentiable=False)
def state_zeros_like(ref, shape=(), batch_axis=0, dtype="float32"):
    """Zeros whose 0-dims are replaced by ref.shape[batch_axis] — resolves
    the reference's unknown-batch (0) recurrent begin_state shapes without
    bidirectional shape inference (symbolic RNN cells, rnn/rnn_cell.py)."""
    import jax
    b = ref.shape[int(batch_axis)]
    resolved = tuple(b if d == 0 else d for d in shape)
    return jnp.zeros(resolved, dtype=np_dtype(dtype or "float32"))


@register("_ones", arg_names=[], differentiable=False)
def ones(shape=(), dtype="float32", ctx=None):
    """Ones-filled tensor of `shape` (reference:
    src/operator/tensor/init_op.cc ones)."""
    return jnp.ones(shape, dtype=np_dtype(dtype or "float32"))


@register("_full", arg_names=[], differentiable=False)
def full(shape=(), value=0.0, dtype="float32", ctx=None):
    """Constant-filled tensor of `shape` (reference:
    src/operator/tensor/init_op.cc full)."""
    return jnp.full(shape, value, dtype=np_dtype(dtype or "float32"))


@register("_arange", arg_names=[], differentiable=False)
def arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", ctx=None,
           infer_range=False):
    """Evenly spaced values in [start, stop) with step and repeat (reference:
    src/operator/tensor/init_op.cc arange)."""
    out = jnp.arange(start, stop, step, dtype=np_dtype(dtype or "float32"))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", arg_names=[], differentiable=False)
def linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32", ctx=None):
    """num evenly spaced samples from start to stop (reference:
    src/operator/tensor/init_op.cc linspace)."""
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=np_dtype(dtype or "float32"))


@register("_eye", arg_names=[], differentiable=False)
def eye(N=0, M=0, k=0, dtype="float32", ctx=None):
    """Identity-matrix constructor (reference: src/operator/tensor/init_op.cc
    eye)."""
    return jnp.eye(int(N), int(M) or None, int(k), dtype=np_dtype(dtype or "float32"))
