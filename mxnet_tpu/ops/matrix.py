"""Shape-manipulation and linear-algebra-entry ops.

Reference: ``src/operator/tensor/matrix_op.cc`` (Reshape/transpose/slice/
concat/stack/tile/repeat/pad/...), ``src/operator/tensor/dot.cc``.
All become jnp/lax calls; XLA's layout assignment replaces the reference's
hand-tuned transpose kernels.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register


@register("Reshape", aliases=("reshape",))
def reshape(data, shape=None, reverse=False):
    """MXNet reshape with special codes 0 (keep), -1 (infer), -2 (copy rest),
    -3 (merge two), -4 (split) — reference matrix_op.cc ReshapeShape."""
    if shape is None:
        return data
    src = list(data.shape)
    if reverse:
        src = src[::-1]
        shape = list(shape)[::-1]
    out = []
    i = 0
    shape = list(shape)
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            elif b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


@register("reshape_like", arg_names=["lhs", "rhs"])
def reshape_like(lhs, rhs):
    """Reshape lhs to rhs's shape (reference:
    src/operator/tensor/elemwise_unary_op_basic.cc reshape_like)."""
    return jnp.reshape(lhs, rhs.shape)


@register("Flatten", aliases=("flatten",))
def flatten(data):
    """Collapse all trailing axes into one: (N, prod(rest)) (reference:
    src/operator/tensor/matrix_op.cc Flatten)."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, axes=None):
    """Permute axes; reverses them when `axes` is empty (reference:
    src/operator/tensor/matrix_op.cc transpose)."""
    if axes is None or axes == ():
        axes = tuple(reversed(range(data.ndim)))
    return jnp.transpose(data, axes)


@register("expand_dims")
def expand_dims(data, axis=0):
    """Insert a size-1 axis at `axis` (reference:
    src/operator/tensor/matrix_op.cc expand_dims)."""
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    """Drop size-1 axes, all or those listed in `axis` (reference:
    src/operator/tensor/matrix_op.cc squeeze)."""
    return jnp.squeeze(data, axis)


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0):
    """Exchange axes dim1 and dim2 (reference: src/operator/swapaxis.cc)."""
    return jnp.swapaxes(data, dim1, dim2)


@register("flip", aliases=("reverse",))
def flip(data, axis=None):
    """Reverse along `axis` (reference: src/operator/tensor/matrix_op.cc
    reverse)."""
    return jnp.flip(data, axis)


@register("tile")
def tile(data, reps=()):
    """Repeat the whole tensor `reps` times per axis (reference:
    src/operator/tensor/matrix_op.cc tile)."""
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None):
    """Repeat each element `repeats` times along `axis` (reference:
    src/operator/tensor/matrix_op.cc repeat)."""
    return jnp.repeat(data, repeats, axis)


@register("Pad", aliases=("pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """Pad spatial axes in constant/edge/reflect mode (reference:
    src/operator/pad.cc)."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    raise ValueError("unknown pad mode %r" % mode)


@register("Concat", arg_names=["args"], aliases=("concat",))
def concat(*args, dim=1, num_args=None):
    """Join inputs along `dim` (reference: src/operator/nn/concat.cc)."""
    return jnp.concatenate(args, axis=dim)


@register("stack", arg_names=["args"])
def stack(*args, axis=0, num_args=None):
    """Stack inputs along a new `axis` (reference:
    src/operator/tensor/matrix_op.cc stack)."""
    return jnp.stack(args, axis=axis)


def _split_num_outputs(params):
    n = params.get("num_outputs")
    if n is None:
        raise ValueError("split requires num_outputs")
    return int(n) if not params.get("squeeze_axis") or True else int(n)


@register("SliceChannel", aliases=("split",), num_outputs=_split_num_outputs)
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    """Split along `axis` into num_outputs equal parts (reference:
    src/operator/slice_channel.cc)."""
    parts = jnp.split(data, int(num_outputs), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    if int(num_outputs) == 1:
        return parts[0]
    return tuple(parts)


@register("slice", aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    """Slice with begin/end/step per axis (reference:
    src/operator/tensor/matrix_op.cc slice)."""
    ndim = data.ndim
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step) + [None] * (ndim - len(step)) if step else [None] * ndim
    idx = tuple(
        slice(b, e, s if s != 0 else None)
        for b, e, s in zip(begin, end, step)
    )
    return data[idx]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    """Slice [begin, end) along a single axis (reference:
    src/operator/tensor/matrix_op.cc slice_axis)."""
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like", arg_names=["data", "shape_like"])
def slice_like(data, shape_like, axes=()):
    """Crop data to shape_like's extent on `axes` (reference:
    src/operator/tensor/matrix_op.cc slice_like)."""
    axes = axes or tuple(range(data.ndim))
    idx = [slice(None)] * data.ndim
    for ax in axes:
        idx[ax] = slice(0, shape_like.shape[ax])
    return data[tuple(idx)]


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    """Broadcast size-1 axes to `size` (reference:
    src/operator/tensor/broadcast_reduce_op_value.cc broadcast_axis)."""
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


@register("broadcast_to")
def broadcast_to(data, shape=()):
    """Broadcast to `shape`; a 0 entry keeps the source dim (reference:
    src/operator/tensor/broadcast_reduce_op_value.cc broadcast_to)."""
    tgt = tuple(d if s == 0 else s for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like", arg_names=["lhs", "rhs"])
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    """Broadcast lhs to rhs's shape on selected axes (reference:
    src/operator/tensor/broadcast_reduce_op_value.cc broadcast_like)."""
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[la] = rhs.shape[ra]
    return jnp.broadcast_to(lhs, tuple(shape))


@register("dot", arg_names=["lhs", "rhs"])
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Matrix/tensor product (reference: src/operator/tensor/dot.cc).

    MXNet dot on >2d contracts last axis of lhs with first of rhs.
    Lowered straight to the MXU via lax.dot_general / jnp.tensordot.
    """
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=1)


@register("batch_dot", arg_names=["lhs", "rhs"])
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matrix product over leading batch dims (reference:
    src/operator/tensor/dot.cc batch_dot)."""
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("depth_to_space")
def depth_to_space(data, block_size=1):
    """Rearrange channel blocks into spatial blocks by block_size (reference:
    src/operator/tensor/matrix_op.cc depth_to_space)."""
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, b, b, c // (b * b), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (n, c // (b * b), h * b, w * b))


@register("space_to_depth")
def space_to_depth(data, block_size=1):
    """Fold spatial blocks into channels; inverse of depth_to_space
    (reference: src/operator/tensor/matrix_op.cc space_to_depth)."""
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


@register("diag")
def diag(data, k=0):
    """Extract a diagonal (2-D+) or build a diagonal matrix (1-D) (reference:
    src/operator/tensor/diag_op.cc)."""
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("shape_array", differentiable=False)
def shape_array(data):
    """Shape of data as a 1-D int tensor (reference:
    src/operator/tensor/elemwise_unary_op_basic.cc shape_array)."""
    return jnp.asarray(data.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array", differentiable=False)
def size_array(data):
    """Element count of data as a 1-D int tensor (reference:
    src/operator/tensor/elemwise_unary_op_basic.cc size_array)."""
    return jnp.asarray([data.size], dtype=jnp.int32)


@register("zeros_like")
def zeros_like(data):
    """Zeros with the shape/dtype of `data` (reference:
    src/operator/tensor/elemwise_unary_op_basic.cc)."""
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    """Ones with the shape/dtype of `data` (reference:
    src/operator/tensor/elemwise_unary_op_basic.cc)."""
    return jnp.ones_like(data)


@register("_ravel_multi_index", arg_names=["data"], differentiable=False,
          aliases=("ravel_multi_index",))
def ravel_multi_index(data, shape=()):
    """(ndim, N) coordinate rows -> flat indices for ``shape``
    (reference: src/operator/tensor/ravel.cc:32)."""
    strides = np.cumprod((list(shape[1:]) + [1])[::-1])[::-1].copy()
    s = jnp.asarray(strides, data.dtype).reshape((-1,) + (1,) * (data.ndim - 1))
    return (data * s).sum(axis=0)


@register("_unravel_index", arg_names=["data"], differentiable=False,
          aliases=("unravel_index",))
def unravel_index(data, shape=()):
    """Flat indices -> (ndim, N) coordinate rows for ``shape``
    (reference: src/operator/tensor/ravel.cc:56)."""
    strides = np.cumprod((list(shape[1:]) + [1])[::-1])[::-1].copy()
    rows = []
    for dim, st in zip(shape, strides):
        rows.append((data // data.dtype.type(int(st))) %
                    data.dtype.type(int(dim)))
    return jnp.stack(rows, axis=0)


def _assign_index(data, begin, end, step):
    ndim = data.ndim
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step) + [None] * (ndim - len(step)) if step else [None] * ndim
    return tuple(slice(b, e, s if s != 0 else None)
                 for b, e, s in zip(begin, end, step))


@register("_slice_assign", arg_names=["lhs", "rhs"])
def slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """Copy of ``lhs`` with ``lhs[begin:end:step] = rhs``
    (reference: src/operator/tensor/matrix_op.cc _slice_assign)."""
    return lhs.at[_assign_index(lhs, begin, end, step)].set(rhs)


@register("_slice_assign_scalar")
def slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    """Copy of ``data`` with the slice filled by ``scalar``
    (reference: matrix_op.cc _slice_assign_scalar)."""
    return data.at[_assign_index(data, begin, end, step)].set(
        data.dtype.type(scalar))
