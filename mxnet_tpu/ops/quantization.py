"""int8 quantization ops + calibration helpers.

Reference: ``src/operator/quantization/`` — quantize/quantize_v2,
dequantize, requantize, quantized_conv/fc (cuDNN int8), and the
calibration graph pass (``quantize_graph_pass.cc``,
``python/mxnet/contrib/quantization.py``).

TPU-native: int8 matmuls hit the MXU natively; the quantized ops keep the
reference's (data, min, max) triple ABI so calibrated models port.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_INT8_MAX = 127.0
_UINT8_MAX = 255.0

_INT8_FLOAT_CHOICES = ("float32", "bfloat16", "float16")


def _int8_float_env():
    """The MXTPU_INT8_FLOAT float-rail dtype, validated on first read so a
    typo fails here with the legal choices instead of as an opaque dtype
    error deep inside a traced op.  Re-read per call (not cached) — but
    note any jitted graph captures the value at trace time."""
    import os
    v = os.environ.get("MXTPU_INT8_FLOAT", "float32")
    if v not in _INT8_FLOAT_CHOICES:
        raise ValueError(
            "MXTPU_INT8_FLOAT=%r invalid; choose one of %s"
            % (v, ", ".join(_INT8_FLOAT_CHOICES)))
    return v


@register("_contrib_quantize", arg_names=["data", "min_range", "max_range"],
          num_outputs=3, differentiable=False, aliases=("quantize",))
def quantize(data, min_range, max_range, out_type="uint8"):
    """Affine quantize to (u)int8 with explicit range
    (reference: quantization/quantize.cc)."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    if out_type == "uint8":
        # degenerate (mx==mn) range → scale 0 not inf: constant data
        # quantizes to code 0 instead of NaN-saturating the graph
        span = mx - mn
        scale = jnp.where(span > 0, _UINT8_MAX / jnp.where(span > 0, span,
                                                           1.0), 0.0)
        q = jnp.clip(jnp.round((data - mn) * scale), 0, 255).astype(jnp.uint8)
    else:
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        scale = jnp.where(amax > 0, _INT8_MAX / jnp.where(amax > 0, amax,
                                                          1.0), 0.0)
        q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, mn.reshape(1), mx.reshape(1)


@register("_contrib_quantize_v2", arg_names=["data"], num_outputs=3,
          differentiable=False, aliases=("quantize_v2",))
def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Quantize with ranges from calibration or the data itself
    (reference: quantize_v2.cc)."""
    if min_calib_range is not None:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    else:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    return quantize(data, mn.reshape(1), mx.reshape(1), out_type=out_type)


@register("_contrib_dequantize", arg_names=["data", "min_range", "max_range"],
          differentiable=False, aliases=("dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    """De-quantize to the float rail.  MXTPU_INT8_FLOAT=bfloat16 narrows
    the inter-layer float tensors (bias/relu/residual chains between
    quantized convs) to the TPU-native half type — the int8 noise floor
    (1/127 per tensor) dwarfs bf16 rounding, and the fp32 elementwise
    round trips are the measured e2e drag of the int8 graph (the scale
    arithmetic itself stays fp32).

    The env override applies only when ``out_type`` is the float32
    default (an explicit out_type wins), is validated by
    ``_int8_float_env`` at first use, and is captured at TRACE time: a
    graph jitted before the env changes keeps the dtype it compiled
    with."""
    fdt = jnp.dtype(_int8_float_env() if out_type == "float32"
                    else out_type)
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    if data.dtype == jnp.uint8:
        scale = (mx - mn) / _UINT8_MAX
        return (data.astype(jnp.float32) * scale + mn).astype(fdt)
    amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    if data.dtype == jnp.int32:
        # int32 accumulator from a quantized matmul
        return (data.astype(jnp.float32)
                * (amax / (2.0 ** 31 - 1))).astype(fdt)
    return (data.astype(jnp.float32) * (amax / _INT8_MAX)).astype(fdt)


@register("_contrib_requantize",
          arg_names=["data", "min_range", "max_range"], num_outputs=3,
          differentiable=False, aliases=("requantize",))
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None, out_type="int8"):
    """int32 accumulator → int8 with calibrated range
    (reference: requantize.cc)."""
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range.reshape(())),
                    jnp.abs(max_range.reshape(()))) / (2.0 ** 31 - 1))
    if min_calib_range is not None:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    else:
        mn = jnp.min(real)
        mx = jnp.max(real)
    amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    scale = jnp.where(amax > 0, _INT8_MAX / jnp.where(amax > 0, amax, 1.0),
                      0.0)
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    return q, mn.reshape(1), mx.reshape(1)


def _qfc_optional(params):
    if params.get("no_bias", False):
        return ("bias", "min_bias", "max_bias")
    return ()


@register("_contrib_quantized_fully_connected",
          arg_names=["data", "weight", "min_data", "max_data",
                     "min_weight", "max_weight", "bias", "min_bias",
                     "max_bias"],
          num_outputs=3, differentiable=False,
          aliases=("quantized_fully_connected",),
          optional_args=_qfc_optional)
def quantized_fully_connected(data, weight, min_data, max_data,
                              min_weight, max_weight, bias=None,
                              min_bias=None, max_bias=None,
                              num_hidden=0, no_bias=False, flatten=True):
    """int8×int8→int32 FC (reference: quantized_fully_connected.cc).
    The int8 dot hits the MXU via preferred_element_type=int32."""
    x = data.astype(jnp.int8)
    if flatten:
        x = x.reshape(x.shape[0], -1)
    # s8 x s8 -> s32 dot: XLA:TPU lowers this to the MXU's native int8
    # matmul path (casting the operands to int32 first would not)
    acc = jax.lax.dot_general(
        x, weight.astype(jnp.int8).T,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(min_data.reshape(())),
                         jnp.abs(max_data.reshape(())))
    w_amax = jnp.maximum(jnp.abs(min_weight.reshape(())),
                         jnp.abs(max_weight.reshape(())))
    out_scale = (d_amax / _INT8_MAX) * (w_amax / _INT8_MAX)
    if bias is not None and not no_bias:
        b_amax = jnp.maximum(jnp.abs(min_bias.reshape(())),
                             jnp.abs(max_bias.reshape(())))
        b_real = bias.astype(jnp.float32) * (b_amax / _INT8_MAX)
        acc = acc + jnp.round(b_real / out_scale).astype(jnp.int32)
    out_max = out_scale * (2.0 ** 31 - 1)
    return acc, -out_max.reshape(1), out_max.reshape(1)


def _qconv_optional(params):
    if params.get("no_bias", True):
        return ("bias", "min_bias", "max_bias")
    return ()


@register("_contrib_quantized_conv",
          arg_names=["data", "weight", "min_data", "max_data",
                     "min_weight", "max_weight", "bias", "min_bias",
                     "max_bias"],
          num_outputs=3, differentiable=False,
          aliases=("quantized_conv",), optional_args=_qconv_optional)
def quantized_conv(data, weight, min_data, max_data, min_weight, max_weight,
                   bias=None, min_bias=None, max_bias=None, kernel=(),
                   stride=(), dilate=(), pad=(), num_filter=0, num_group=1,
                   no_bias=True, layout=None, workspace=1024,
                   cudnn_tune=None, cudnn_off=False):
    """int8×int8→int32 convolution (reference: quantized_conv.cu).  The
    integer conv hits the MXU with an int32 accumulator; output carries the
    (min, max) range of the int32 domain like the reference."""
    from jax import lax
    from .nn import _tup, _conv_layout

    nsp = len(kernel) if kernel else data.ndim - 2
    stride = _tup(stride, nsp) if stride else (1,) * nsp
    dilate = _tup(dilate, nsp) if dilate else (1,) * nsp
    pad = _tup(pad, nsp) if pad else (0,) * nsp
    dimnum, channels_last = _conv_layout(layout, nsp)
    x = data.astype(jnp.int8)
    w = weight.astype(jnp.int8)
    if (channels_last and all(k == 1 for k in kernel) and num_group == 1
            and all(p == 0 for p in pad)):
        # 1x1 conv in NHWC == matmul over the channel axis.  XLA:TPU's int8
        # *conv* lowering is ~6x slower than bf16 here, but its int8
        # dot_general is the fastest path on chip — so lower it ourselves.
        # weight is (O, *1s, I) channels-last; stride handled by slicing.
        if any(s != 1 for s in stride):
            sl = (slice(None),) + tuple(slice(None, None, s) for s in stride)
            x = x[sl]
        wf = w.reshape(w.shape[0], w.shape[-1]).T  # (I, O)
        acc = lax.dot_general(x, wf, (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    else:
        # s8 x s8 conv with an s32 accumulator stays on the MXU int8 path
        # (casting operands to int32 first forces a slow integer fallback)
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, dimnum)
        acc = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=[(p, p) for p in pad],
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=int(num_group),
            preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(min_data.reshape(())),
                         jnp.abs(max_data.reshape(())))
    w_amax = jnp.maximum(jnp.abs(min_weight.reshape(())),
                         jnp.abs(max_weight.reshape(())))
    out_scale = (d_amax / _INT8_MAX) * (w_amax / _INT8_MAX)
    if bias is not None and not no_bias:
        b_amax = jnp.maximum(jnp.abs(min_bias.reshape(())),
                             jnp.abs(max_bias.reshape(())))
        b_real = bias.astype(jnp.float32) * (b_amax / _INT8_MAX)
        bshape = (1,) * (nsp + 1) + (-1,) if channels_last \
            else (1, -1) + (1,) * nsp
        acc = acc + jnp.round(b_real / out_scale).astype(jnp.int32) \
            .reshape(bshape)
    out_max = out_scale * (2.0 ** 31 - 1)
    return acc, -out_max.reshape(1), out_max.reshape(1)


@register("_contrib_quantized_pooling",
          arg_names=["data", "min_data", "max_data"], num_outputs=3,
          differentiable=False, aliases=("quantized_pooling",))
def quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                      global_pool=False, pooling_convention="valid",
                      stride=(), pad=(), count_include_pad=True,
                      layout=None, cudnn_off=False):
    """Pooling on int8 tensors (reference: quantized_pooling.cc): max pool
    compares int8 directly; avg pool accumulates in int32 and rounds back.
    The (min, max) range passes through unchanged."""
    from .nn import pooling

    if pool_type == "max":
        out = pooling(data, kernel=kernel, pool_type="max",
                      global_pool=global_pool,
                      pooling_convention=pooling_convention, stride=stride,
                      pad=pad, layout=layout)
    else:
        acc = pooling(data.astype(jnp.int32), kernel=kernel, pool_type="sum",
                      global_pool=global_pool,
                      pooling_convention=pooling_convention, stride=stride,
                      pad=pad, layout=layout)
        if global_pool:
            sp = data.shape[1:-1] if layout in ("NWC", "NHWC", "NDHWC") \
                else data.shape[2:]
            denom = 1
            for s in sp:
                denom *= s
        else:
            denom = 1
            for k in (kernel if kernel else ()):
                denom *= k
        out = jnp.clip(jnp.round(acc / denom), -127, 127).astype(data.dtype)
    return out, min_data.reshape(1), max_data.reshape(1)


def _qfcpc_optional(params):
    if params.get("no_bias", False):
        return ("bias",)
    return ()


@register("_contrib_quantized_fc_pc",
          arg_names=["data", "weight", "w_scale", "bias"],
          differentiable=False, aliases=("quantized_fc_pc",),
          optional_args=_qfcpc_optional)
def quantized_fc_pc(data, weight, w_scale, bias=None, num_hidden=0,
                    in_amax=1.0, relu=False, no_bias=False, flatten=True):
    """Per-channel int8 FC with the dequant epilogue fused — the
    ``qmm_requant`` kernel lineage (ops/pallas_kernels.py) applied to the
    PTQ pipeline (serving/quantize.py, docs/precision.md).

    ``weight`` is int8 codes quantized per OUTPUT CHANNEL:
    ``w_real[c] = codes[c] * w_scale[c]`` with ``w_scale`` an ``(O,)``
    f32 vector — one outlier row no longer poisons every channel's
    resolution the way the reference's per-tensor (min, max) pair does.
    The f32 activation quantizes on entry against the CALIBRATED
    ``in_amax`` (a trace-time constant from the calibration set), the
    s8×s8→s32 dot rides the MXU, and the epilogue
    ``acc * (in_scale * w_scale[c]) + bias → [relu]`` lands back on the
    float rail in the same fusion — the int32 accumulator never touches
    HBM.  Output stays float (the measured-faster split-graph
    discipline: contrib/quantization.py keeps requantize chains out of
    XLA's way)."""
    in_scale = float(in_amax) / _INT8_MAX
    if in_scale <= 0.0:
        in_scale = 1.0 / _INT8_MAX
    x = data
    if flatten:
        x = x.reshape(x.shape[0], -1)
    codes = jnp.clip(jnp.round(x.astype(jnp.float32) / in_scale),
                     -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        codes, weight.astype(jnp.int8).T,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) \
        * (in_scale * w_scale.astype(jnp.float32))[None, :]
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32)[None, :]
    if relu:
        out = jnp.maximum(out, 0.0)
    fdt = jnp.dtype(_int8_float_env())
    return out.astype(fdt)


def calib_minmax(arrays):
    """Min/max calibration over representative activations
    (reference: contrib/quantization.py _collect_layer_output_min_max)."""
    import numpy as np
    mn = min(float(np.min(a.asnumpy() if hasattr(a, "asnumpy") else a))
             for a in arrays)
    mx = max(float(np.max(a.asnumpy() if hasattr(a, "asnumpy") else a))
             for a in arrays)
    return mn, mx


@register("_contrib_quantized_flatten",
          arg_names=["data", "min_data", "max_data"], num_outputs=3,
          differentiable=False, aliases=("quantized_flatten",))
def quantized_flatten(data, min_data, max_data):
    """Flatten on the int8 tensor; the range rides through
    (reference: src/operator/quantization/quantized_flatten.cc:31)."""
    return (data.reshape(data.shape[0], -1), min_data.reshape(1),
            max_data.reshape(1))
