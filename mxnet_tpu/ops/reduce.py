"""Reduction and ordering ops.

Reference: ``src/operator/tensor/broadcast_reduce_op*`` (sum/mean/prod/norm
with keepdims/exclude), ``src/operator/tensor/ordering_op*`` (topk/sort/
argsort).  jnp reductions lower to XLA reduce; safe accumulation (the
reference's MXNET_SAFE_ACCUMULATION) maps to accumulating low-precision
inputs in float32.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _axes(data, axis, exclude=False):
    if axis is None or axis == ():
        ax = tuple(range(data.ndim))
    elif isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(axis)
    ax = tuple(a % data.ndim for a in ax)
    if exclude:
        ax = tuple(i for i in range(data.ndim) if i not in ax)
    return ax


def _reduce(name, jfn):
    @register(name,
              doc="Reduce %s over `axis` with keepdims/exclude (reference: "
                  "src/operator/tensor/broadcast_reduce_op_value.cc); "
                  "lowers to one XLA reduce." % name)
    def fn(data, axis=None, keepdims=False, exclude=False, __jfn=jfn):
        return __jfn(data, axis=_axes(data, axis, exclude), keepdims=keepdims)
    fn.__name__ = name
    return fn


_reduce("sum", jnp.sum)
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max)
_reduce("min", jnp.min)

from .registry import alias
alias("sum", "sum_axis")
alias("max", "max_axis")
alias("min", "min_axis")


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    """L1/L2 norm over `axis`, accumulating low-precision inputs in float32
    (reference: src/operator/tensor/broadcast_reduce_op_value.cc norm)."""
    ax = None if axis is None or axis == () else axis
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    acc = data.astype(jnp.float32) if data.dtype in (jnp.float16, jnp.bfloat16) else data
    out = jnp.sqrt(jnp.sum(jnp.square(acc), axis=ax, keepdims=keepdims))
    return out.astype(data.dtype)


@register("argmax", differentiable=False)
def argmax(data, axis=None, keepdims=False):
    """Index of the maximum along `axis`, returned as float32 (reference:
    src/operator/tensor/broadcast_reduce_op_index.cc)."""
    out = jnp.argmax(data, axis=axis).astype(jnp.float32)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


@register("argmin", differentiable=False)
def argmin(data, axis=None, keepdims=False):
    """Index of the minimum along `axis`, returned as float32 (reference:
    src/operator/tensor/broadcast_reduce_op_index.cc)."""
    out = jnp.argmin(data, axis=axis).astype(jnp.float32)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


@register("argmax_channel", differentiable=False)
def argmax_channel(data):
    """argmax over axis 1, the channel axis (reference:
    src/operator/tensor/broadcast_reduce_op_index.cc argmax_channel)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register("topk", differentiable=False,
          num_outputs=lambda p: 2 if p.get("ret_typ", "indices") == "both" else 1)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Reference: src/operator/tensor/ordering_op.cc TopK."""
    from ..base import np_dtype
    x = jnp.moveaxis(data, axis, -1)
    if is_ascend:
        vals, idx = jax._topk_neg(x, k) if False else _topk_ascend(x, k)
    else:
        import jax.lax as lax
        vals, idx = lax.top_k(x, k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(np_dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        x2 = jnp.moveaxis(jnp.zeros_like(data), axis, -1)
        ii = jnp.moveaxis(idx, axis, -1).astype(jnp.int32)
        mask = jnp.take_along_axis(x2, ii, axis=-1) * 0 + 1  # placeholder
        out = jnp.zeros_like(x2).at[..., :].set(0)
        out = jnp.put_along_axis(out, ii, 1.0, axis=-1, inplace=False) if hasattr(jnp, "put_along_axis") else _scatter_mask(out, ii)
        return jnp.moveaxis(out, -1, axis)
    raise ValueError(ret_typ)


def _topk_ascend(x, k):
    import jax.lax as lax
    vals, idx = lax.top_k(-x, k)
    return -vals, idx


def _scatter_mask(zeros, idx):
    oh = jnp.sum(jax.nn.one_hot(idx, zeros.shape[-1], dtype=zeros.dtype), axis=-2)
    return jnp.clip(oh, 0, 1)


import jax  # noqa: E402  (used by topk mask path)


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    """Sorted copy along `axis` (reference: src/operator/tensor/ordering_op.cc
    sort)."""
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    """Indices that would sort along `axis` (reference:
    src/operator/tensor/ordering_op.cc argsort)."""
    from ..base import np_dtype
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(np_dtype(dtype))
