"""Neural-network operators lowered onto XLA's conv/reduce-window/dot HLOs.

Reference: ``src/operator/nn/`` — Convolution (convolution-inl.h + cudnn
wrappers), FullyConnected, Pooling (pool.cuh), BatchNorm, LayerNorm, Dropout,
activation/softmax families, plus spatial ops from ``src/operator/``.
Where the reference dispatches to cuDNN with an algo-autotune registry
(cudnn_algoreg-inl.h), we emit a single lax.conv_general_dilated and let XLA
pick MXU tilings — convs and FC land on the MXU in bf16/fp32 per input dtype.
"""
from __future__ import annotations

from functools import partial as _partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import _rng
from .registry import register


def _tup(v, n):
    if v is None or v == ():
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------
@register("FullyConnected", arg_names=["data", "weight", "bias"])
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """Reference: src/operator/nn/fully_connected.cc.  weight is
    (num_hidden, input_dim) as in the reference; lowers to one MXU matmul."""
    if flatten and data.ndim > 2:
        data = jnp.reshape(data, (data.shape[0], -1))
    out = lax.dot_general(
        data, weight,
        dimension_numbers=(((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32 if data.dtype == jnp.bfloat16 else None,
    ).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------
_CONV_DIMNUM = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
                3: ("NCDHW", "OIDHW", "NCDHW")}
# channels-last layouts (reference supports NHWC/NWC via the layout param;
# on TPU this is the native tiling — no internal transposes).  MXNet weight
# layout for channels-last convs is (num_filter, *kernel, C/group).
_CONV_DIMNUM_CL = {1: ("NHC", "OHI", "NHC"), 2: ("NHWC", "OHWI", "NHWC"),
                   3: ("NDHWC", "ODHWI", "NDHWC")}
_CHANNELS_LAST = {"NWC", "NHWC", "NDHWC"}


def _conv_layout(layout, nsp):
    if layout in _CHANNELS_LAST:
        return _CONV_DIMNUM_CL[nsp], True
    return _CONV_DIMNUM[nsp], False


@register("Convolution", arg_names=["data", "weight", "bias"])
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, workspace=1024,
                no_bias=False, cudnn_tune=None, cudnn_off=False, layout=None):
    """Reference: src/operator/nn/convolution.cc; weight layout
    (num_filter, C/group, *kernel) identical to the reference, or
    (num_filter, *kernel, C/group) for channels-last layouts."""
    nsp = len(kernel) if kernel else data.ndim - 2
    stride = _tup(stride, nsp)
    dilate = _tup(dilate, nsp)
    pad = _tup(pad, nsp) if pad else (0,) * nsp
    dimnum, channels_last = _conv_layout(layout, nsp)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, dimnum)
    # mixed float dtypes reconcile to the DATA's dtype (reference fp16
    # path: fp32 master weights cast at the kernel boundary) — lets a
    # bf16 activation rail run against fp32 checkpoint params
    if weight.dtype != data.dtype and jnp.issubdtype(data.dtype, jnp.floating) \
            and jnp.issubdtype(weight.dtype, jnp.floating):
        weight = weight.astype(data.dtype)
    if bias is not None and bias.dtype != data.dtype and \
            jnp.issubdtype(data.dtype, jnp.floating) and \
            jnp.issubdtype(bias.dtype, jnp.floating):
        bias = bias.astype(data.dtype)
    # no preferred_element_type upcast for bf16: the MXU accumulates bf16
    # convs in fp32 natively, and jax 0.9's conv transpose rule rejects the
    # fp32-cotangent/bf16-operand mix it would create
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=int(num_group),
    ).astype(data.dtype)
    if bias is not None and not no_bias:
        bshape = (1,) * (nsp + 1) + (-1,) if channels_last \
            else (1, -1) + (1,) * nsp
        out = out + jnp.reshape(bias, bshape)
    return out


def _deconv_optional(params):
    # reference default is no_bias=True: the bias var only exists when
    # bias is requested (matches _deconv_param_shapes in symbol.py)
    if params.get("no_bias", True):
        return ("bias",)
    return ()


@register("Deconvolution", arg_names=["data", "weight", "bias"],
          optional_args=_deconv_optional)
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0, num_group=1,
                  workspace=512, no_bias=True, cudnn_tune=None, cudnn_off=False,
                  layout=None):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc).
    Weight layout (C_in, C_out/group, *kernel); implemented as an
    input-dilated forward conv, which XLA lowers to the same MXU program it
    uses for conv backward-data."""
    nsp = len(kernel)
    if layout in _CHANNELS_LAST:
        # weight keeps the reference's channels-first (C_in, C_out/g, *k)
        # shape; only the data layout differs, so route through the
        # channels-first path (deconv is never the hot op)
        perm_in = (0, data.ndim - 1) + tuple(range(1, data.ndim - 1))
        perm_out = (0,) + tuple(range(2, data.ndim)) + (1,)
        out = deconvolution(
            jnp.transpose(data, perm_in), weight, bias, kernel=kernel,
            stride=stride, dilate=dilate, pad=pad, adj=adj,
            target_shape=target_shape, num_filter=num_filter,
            num_group=num_group, workspace=workspace, no_bias=no_bias,
            layout=None)
        return jnp.transpose(out, perm_out)
    stride = _tup(stride, nsp)
    dilate = _tup(dilate, nsp)
    pad = _tup(pad, nsp) if pad else (0,) * nsp
    adj = _tup(adj, nsp) if adj else (0,) * nsp
    g = int(num_group)
    cin = weight.shape[0]
    cog = weight.shape[1]
    # (C_in, C_out/g, *k) -> (C_out, C_in/g, *k), spatially flipped
    w = jnp.reshape(weight, (g, cin // g, cog) + weight.shape[2:])
    w = jnp.swapaxes(w, 1, 2)
    w = jnp.reshape(w, (g * cog, cin // g) + weight.shape[2:])
    w = jnp.flip(w, axis=tuple(range(2, 2 + nsp)))
    eff_k = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilate))
    padding = [(ek - 1 - p, ek - 1 - p + a) for ek, p, a in zip(eff_k, pad, adj)]
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _CONV_DIMNUM[nsp])
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nsp, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=g,
    ).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * nsp)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------
@_partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool(data, window, strides, padding):
    return lax.reduce_window(data, np.asarray(-jnp.inf, data.dtype)[()],
                             lax.max, window, strides, padding)


def _max_pool_fwd(data, window, strides, padding):
    y = _max_pool(data, window, strides, padding)
    return y, (data, y)


def _max_pool_bwd(window, strides, padding, res, dy):
    """Offset-sum maxpool backward: for every in-window offset, the input
    slice aligned with the output grid receives ``dy`` where it equals the
    window max.  Replaces XLA's select_and_scatter (2x faster on TPU;
    ties get the gradient at every max position, like the reference's CPU
    pool backward in src/operator/nn/pool.h)."""
    import itertools
    x, y = res
    xp = jnp.pad(x, padding, constant_values=np.asarray(-jnp.inf, x.dtype)[()])
    dxp = jnp.zeros(xp.shape, dy.dtype)
    out_shape = y.shape
    for off in itertools.product(*[range(w) for w in window]):
        limit = tuple(o + (os - 1) * s + 1
                      for o, os, s in zip(off, out_shape, strides))
        xs = lax.slice(xp, off, limit, strides)
        contrib = jnp.where(xs == y, dy, np.asarray(0, dy.dtype)[()])
        dxp = dxp.at[tuple(slice(o, l, s)
                           for o, l, s in zip(off, limit, strides))] \
            .add(contrib)
    unpad = tuple(slice(lo, dim - hi)
                  for (lo, hi), dim in zip(padding, xp.shape))
    return (dxp[unpad],)


_max_pool.defvjp(_max_pool_fwd, _max_pool_bwd)


@register("Pooling", arg_names=["data"])
def pooling(data, kernel=(), pool_type="max", global_pool=False, cudnn_off=False,
            pooling_convention="valid", stride=(), pad=(), count_include_pad=True,
            layout=None):
    """Reference: src/operator/nn/pooling.cc (+ pool.cuh kernels).
    max/avg/sum over reduce_window; 'full' convention (ceil) adds high-side
    padding exactly as the reference's pooling shape rule.  ``layout``
    accepts the channels-last forms (NWC/NHWC/NDHWC) natively."""
    nsp = data.ndim - 2
    channels_last = layout in _CHANNELS_LAST
    sp0 = 1 if channels_last else 2  # first spatial dim index
    if global_pool:
        kernel = data.shape[sp0:sp0 + nsp]
        stride = (1,) * nsp
        pad = (0,) * nsp
    kernel = _tup(kernel, nsp)
    stride = _tup(stride, nsp) if stride else (1,) * nsp
    pad = _tup(pad, nsp) if pad else (0,) * nsp
    extra = [0] * nsp
    if pooling_convention == "full" and not global_pool:
        for i in range(nsp):
            insz = data.shape[sp0 + i]
            out_sz = int(np.ceil((insz + 2 * pad[i] - kernel[i]) / stride[i])) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - (insz + 2 * pad[i])
            extra[i] = max(0, need)
    sp_pad = [(p, p + e) for p, e in zip(pad, extra)]
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        padding = [(0, 0)] + sp_pad + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        padding = [(0, 0), (0, 0)] + sp_pad
    if pool_type == "max":
        import os as _os
        if jnp.issubdtype(data.dtype, jnp.floating) and not global_pool \
                and _os.environ.get("MXTPU_MAXPOOL_VJP", "0") == "1":
            # opt-in custom-VJP path: the offset-sum backward beats XLA's
            # select_and_scatter 2x in isolation (0.051 vs 0.103 ms at
            # 256x112x112x64) and matches the reference CPU kernel's
            # grad-to-every-tied-max semantics (src/operator/nn/pool.h),
            # but inside the full resnet-50 training graph it measures 7%
            # SLOWER end to end (its 9 strided scatter-adds break XLA's
            # backward fusion) — docs/perf_resnet50_tpu.md "levers
            # measured and rejected".  Default: select_and_scatter.
            return _max_pool(data, window, strides, tuple(padding))
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, np.asarray(init, data.dtype)[()], lax.max,
                                 window, strides, padding)
    summed = lax.reduce_window(data, np.asarray(0, data.dtype)[()], lax.add,
                               window, strides, padding)
    if pool_type == "sum":
        return summed
    if pool_type == "avg":
        if count_include_pad:
            denom = float(np.prod(kernel))
            return summed / jnp.asarray(denom, data.dtype)
        ones = jnp.ones(data.shape, data.dtype)
        counts = lax.reduce_window(ones, np.asarray(0, data.dtype)[()], lax.add,
                                   window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        p = 2.0
        pw = lax.reduce_window(jnp.abs(data) ** p, np.asarray(0, data.dtype)[()],
                               lax.add, window, strides, padding)
        return pw ** (1.0 / p)
    raise ValueError("unknown pool_type %r" % pool_type)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------
def _bn_moving_update(inputs, outputs, params):
    momentum = params.get("momentum", 0.9)
    _, _, _, mmean, mvar = inputs[:5]
    _, bmean, bvar = outputs[:3]
    return {
        3: momentum * mmean + (1 - momentum) * bmean,
        4: momentum * mvar + (1 - momentum) * bvar,
    }


def _bn_stats(x, red):
    """Batch mean/var accumulated in fp32.  For bf16/fp16 inputs this is a
    single fused read (E[x], E[x^2]); fp32 keeps the two-pass form to avoid
    E[x^2]-E[x]^2 cancellation."""
    if x.dtype in (jnp.float16, jnp.bfloat16):
        mean = jnp.mean(x, axis=red, dtype=jnp.float32)
        m2 = jnp.mean(lax.square(x.astype(jnp.float32)), axis=red)
        var = jax.nn.relu(m2 - lax.square(mean))
    else:
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red)
        var = jnp.var(x32, axis=red)
    return mean, var


def _bn_apply(x, scale, shift, axis):
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return x * scale.reshape(shape).astype(x.dtype) \
        + shift.reshape(shape).astype(x.dtype)


@_partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, gamma, beta, eps, axis):
    red = tuple(i for i in range(x.ndim) if i != axis)
    mean, var = _bn_stats(x, red)
    inv = lax.rsqrt(var + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean * scale
    return _bn_apply(x, scale, shift, axis), mean, var


def _bn_train_fwd(x, gamma, beta, eps, axis):
    out, mean, var = _bn_train(x, gamma, beta, eps, axis)
    return (out, mean, var), (x, gamma, mean, var)


def _bn_train_bwd(eps, axis, res, cts):
    """Hand-derived BN backward: one fused reduction pass over (g, x) and one
    elementwise pass dx = A*g + B*x + C with per-channel A/B/C — the minimal
    HBM traffic form (autodiff of the stats emits extra full-tensor passes).
    Reference semantics: src/operator/nn/batch_norm.cc backward."""
    g_out, ct_mean, ct_var = cts
    x, gamma, mean, var = res
    red = tuple(i for i in range(x.ndim) if i != axis)
    n = 1
    for i in red:
        n *= x.shape[i]
    inv = lax.rsqrt(var + eps)
    # one fused pass: both reductions read (g, x) together
    sum_g = jnp.sum(g_out, axis=red, dtype=jnp.float32)
    sum_gx = jnp.sum(g_out.astype(jnp.float32) * x.astype(jnp.float32),
                     axis=red)
    sum_gxhat = (sum_gx - mean * sum_g) * inv
    g32 = gamma.astype(jnp.float32)
    dgamma = sum_gxhat.astype(gamma.dtype)
    dbeta = sum_g.astype(gamma.dtype)
    # dx = gamma*inv*(g - sum_g/n - xhat*sum_gxhat/n)  (+ mean/var cotangent
    # terms, which XLA folds away when those outputs are unused)
    A = g32 * inv
    B = -g32 * inv * inv * sum_gxhat / n \
        + 2.0 * ct_var.astype(jnp.float32) / n
    C = -A * sum_g / n + g32 * inv * inv * mean * sum_gxhat / n \
        + ct_mean.astype(jnp.float32) / n \
        - 2.0 * ct_var.astype(jnp.float32) * mean / n
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    dx = (g_out * A.reshape(shape).astype(x.dtype)
          + x * B.reshape(shape).astype(x.dtype)
          + C.reshape(shape).astype(x.dtype))
    return dx, dgamma, dbeta


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register("BatchNorm", arg_names=["data", "gamma", "beta"],
          aux={3: "moving_mean", 4: "moving_var"}, aux_update=_bn_moving_update,
          num_outputs=lambda p: 3 if p.get("output_mean_var") else 1,
          needs_train=True)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, _train=False):
    """Reference: src/operator/nn/batch_norm.cc.  Under training uses batch
    stats (moving stats updated via aux_update); under inference uses the
    moving stats.  fix_gamma pins gamma to 1 as the reference does."""
    axis = axis % data.ndim
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if _train and not use_global_stats:
        # mean/var stay fp32: the moving-stat update (aux_update) and any
        # output_mean_var consumer get full-precision statistics even under
        # bf16 training, as the reference's fp16 path does
        out, mean, var = _bn_train(data, g, beta, float(eps), axis)
        return out, mean, var
    mean = moving_mean.astype(jnp.float32)
    var = moving_var.astype(jnp.float32)
    inv = lax.rsqrt(var + eps) * g.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean * inv
    out = _bn_apply(data, inv, shift, axis)
    return out, mean, var


@register("LayerNorm", arg_names=["data", "gamma", "beta"])
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Reference: src/operator/nn/layer_norm.cc."""
    axis = axis % data.ndim
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.var(x32, axis=axis, keepdims=True)
    out = (x32 - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = out * gamma.astype(jnp.float32).reshape(shape) + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)


@register("InstanceNorm", arg_names=["data", "gamma", "beta"])
def instance_norm(data, gamma, beta, eps=1e-3):
    """Reference: src/operator/instance_norm.cc — normalize over spatial dims
    per (N, C)."""
    red = tuple(range(2, data.ndim))
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=red, keepdims=True)
    var = jnp.var(x32, axis=red, keepdims=True)
    out = (x32 - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    out = out * gamma.reshape(shape) + beta.reshape(shape)
    return out.astype(data.dtype)


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    """Reference: src/operator/l2_normalization.cc."""
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        kd = True
    elif mode == "channel":
        red = (1,)
        kd = True
    elif mode == "spatial":
        red = tuple(range(2, data.ndim))
        kd = True
    else:
        raise ValueError(mode)
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=kd) + eps)
    return data / nrm


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (reference: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    sqp = jnp.pad(sq, pad)
    acc = sum(
        lax.slice_in_dim(sqp, i, i + data.shape[1], axis=1) for i in range(nsize)
    )
    return data / jnp.power(knorm + alpha / nsize * acc, beta)


# ---------------------------------------------------------------------------
# Activations / softmax
# ---------------------------------------------------------------------------
@register("Activation")
def activation(data, act_type="relu"):
    """Apply the `act_type` nonlinearity (relu/sigmoid/tanh/softrelu/softsign)
    (reference: src/operator/nn/activation.cc)."""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %r" % act_type)


@register("LeakyReLU", arg_names=["data", "gamma"], needs_train=True)
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334, _train=False):
    """Reference: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/rrelu)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and g.ndim == 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data)
    if act_type == "rrelu":
        if _train:
            u = jax.random.uniform(_rng.next_key(), data.shape, data.dtype,
                                   lower_bound, upper_bound)
            return jnp.where(data > 0, data, u * data)
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise ValueError(act_type)


def _softmax_io(data, dtype):
    """Half-precision softmax accumulates in fp32 (reference:
    src/operator/nn/softmax-inl.h AType) and returns the input dtype unless
    ``dtype`` overrides the output type."""
    out_dtype = jnp.dtype(dtype) if dtype is not None else data.dtype
    if data.dtype in (jnp.float16, jnp.bfloat16):
        data = data.astype(jnp.float32)
    return data, out_dtype


@register("softmax")
def softmax(data, axis=-1, temperature=None, dtype=None):
    """Normalized exponentials along `axis` with optional temperature
    (reference: src/operator/nn/softmax.cc)."""
    data, out_dtype = _softmax_io(data, dtype)
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=axis).astype(out_dtype)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None):
    """Numerically stable log(softmax(data)) along `axis` (reference:
    src/operator/nn/softmax.cc log_softmax)."""
    data, out_dtype = _softmax_io(data, dtype)
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis).astype(out_dtype)


@register("softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    """softmax of the negated input (reference: src/operator/nn/softmax.cc
    softmin)."""
    data, out_dtype = _softmax_io(data, dtype)
    return jax.nn.softmax(-data, axis=axis).astype(out_dtype)


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    """Softmax over the channel (or flattened instance) axis; deprecated alias
    family of softmax (reference: src/operator/nn/softmax_activation.cc)."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


# -- output heads with custom backward semantics ---------------------------
from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                         multi_output, normalization_valid, smooth_alpha):
    axis = 1 if multi_output else -1
    return jax.nn.softmax(data, axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output, normalization_valid, smooth_alpha):
    out = _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                               multi_output, normalization_valid, smooth_alpha)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, multi_output,
                        normalization_valid, smooth_alpha, res, g):
    out, label = res
    axis = 1 if multi_output else -1
    nclass = out.shape[axis]
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, nclass, axis=axis, dtype=out.dtype)
    if smooth_alpha:
        onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (nclass - 1) * (1 - onehot)
    grad = out - onehot
    scale = grad_scale
    if use_ignore:
        keep = (lab != int(ignore_label)).astype(out.dtype)
        grad = grad * jnp.expand_dims(keep, axis)
        if normalization_valid:
            scale = scale * lab.size / jnp.maximum(jnp.sum(keep), 1.0)
    elif normalization_valid:
        scale = scale / lab.size * out.shape[0]  # 'valid' == batch when no ignore
    grad = grad * scale
    return (grad, jnp.zeros_like(label))


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", arg_names=["data", "label"], aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Softmax forward whose *backward* is (p - onehot(label)) — the
    reference's fused classification head (src/operator/softmax_output.cc)."""
    return _softmax_output_core(
        data, label, grad_scale, ignore_label, use_ignore, multi_output,
        normalization == "valid", smooth_alpha)


def _regression_output(transform, grad_fn):
    @_partial(jax.custom_vjp, nondiff_argnums=(2,))
    def core(data, label, grad_scale):
        return transform(data)

    def fwd(data, label, grad_scale):
        return core(data, label, grad_scale), (transform(data), label)

    def bwd(grad_scale, res, g):
        out, label = res
        num_out = out.size // out.shape[0]
        grad = grad_fn(out, label.reshape(out.shape)) * grad_scale / num_out
        return grad, jnp.zeros_like(label)

    core.defvjp(fwd, bwd)
    return core


_linear_reg = _regression_output(lambda x: x, lambda o, l: o - l)
_mae_reg = _regression_output(lambda x: x, lambda o, l: jnp.sign(o - l))
_logistic_reg = _regression_output(jax.nn.sigmoid, lambda o, l: o - l)


@register("LinearRegressionOutput", arg_names=["data", "label"])
def linear_regression_output(data, label, grad_scale=1.0):
    """L2 regression head: forward is identity, gradient is data - label
    (reference: src/operator/regression_output-inl.h)."""
    return _linear_reg(data, label, grad_scale)


@register("MAERegressionOutput", arg_names=["data", "label"])
def mae_regression_output(data, label, grad_scale=1.0):
    """L1 regression head with sign(data - label) gradient (reference:
    src/operator/regression_output-inl.h)."""
    return _mae_reg(data, label, grad_scale)


@register("LogisticRegressionOutput", arg_names=["data", "label"])
def logistic_regression_output(data, label, grad_scale=1.0):
    """Sigmoid regression head with sigmoid(data) - label gradient (reference:
    src/operator/regression_output-inl.h)."""
    return _logistic_reg(data, label, grad_scale)


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def _make_loss_core(data, grad_scale):
    return data


def _make_loss_fwd(data, grad_scale):
    return data, None


def _make_loss_bwd(grad_scale, res, g):
    return (jnp.full(g.shape, grad_scale, g.dtype),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Forward identity; backward is grad_scale regardless of head grad
    (reference: src/operator/make_loss.cc)."""
    scale = grad_scale
    if normalization == "batch":
        scale = grad_scale / data.shape[0]
    return _make_loss_core(data, scale)


@register("softmax_cross_entropy", arg_names=["data", "label"])
def softmax_cross_entropy(data, label):
    """Fused softmax + cross-entropy scalar loss (reference:
    src/operator/loss_binary_op.cc)."""
    lp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(lp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


@register("Dropout", needs_train=True)
def dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, _train=False):
    """Reference: src/operator/nn/dropout.cc — inverted dropout."""
    if (not _train and mode != "always") or p == 0:
        return data
    shape = list(data.shape)
    if axes:
        for i in range(len(shape)):
            if i not in axes:
                shape[i] = 1 if False else shape[i]
        shape = [1 if i in axes else s for i, s in enumerate(data.shape)]
    mask = jax.random.bernoulli(_rng.next_key(), 1.0 - p, tuple(shape))
    return jnp.where(mask, data / (1.0 - p), jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# Spatial ops
# ---------------------------------------------------------------------------
@register("UpSampling", arg_names=["args"])
def upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=512):
    """Reference: src/operator/upsampling.cc."""
    outs = []
    for data in args:
        if sample_type == "nearest":
            out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        else:
            n, c, h, w = data.shape
            out = jax.image.resize(data, (n, c, h * scale, w * scale), "bilinear")
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if multi_input_mode == "sum":
        return sum(outs)
    return jnp.concatenate(outs, axis=1)


@register("Crop", arg_names=["args"], aliases=())
def crop_sym(*args, num_args=1, offset=(0, 0), h_w=(0, 0), center_crop=False):
    """Reference: src/operator/crop.cc."""
    data = args[0]
    if num_args == 2 or len(args) == 2:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = h_w
    if center_crop:
        oh = (data.shape[2] - th) // 2
        ow = (data.shape[3] - tw) // 2
    else:
        oh, ow = offset
    return data[:, :, oh:oh + th, ow:ow + tw]


@register("GridGenerator", arg_names=["data"])
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Reference: src/operator/grid_generator.cc — outputs (N, 2, H, W) grid
    in [-1, 1] coords (x, y)."""
    if transform_type == "affine":
        n = data.shape[0]
        h, w = target_shape
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, base)  # (N, 2, HW)
        return out.reshape(n, 2, h, w)
    if transform_type == "warp":
        flow = data  # (N, 2, H, W) pixel offsets
        n, _, h, w = flow.shape
        gy, gx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
        x = (gx[None] + flow[:, 0]) * 2.0 / jnp.maximum(w - 1, 1) - 1.0
        y = (gy[None] + flow[:, 1]) * 2.0 / jnp.maximum(h - 1, 1) - 1.0
        return jnp.stack([x, y], axis=1)
    raise ValueError(transform_type)


def _bilinear_gather(data, x, y):
    """Sample data (N,C,H,W) at float pixel coords x,y (N,Ho,Wo)."""
    n, c, h, w = data.shape
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0
    out = 0
    for dy, dx in ((0, 0), (0, 1), (1, 0), (1, 1)):
        xi = x0 + dx
        yi = y0 + dy
        wgt = (wx if dx else 1 - wx) * (wy if dy else 1 - wy)
        valid = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        vals = data[jnp.arange(n)[:, None, None], :, yi_c, xi_c]  # (N,Ho,Wo,C)
        out = out + vals * (wgt * valid)[..., None]
    return jnp.moveaxis(out, -1, 1)


@register("BilinearSampler", arg_names=["data", "grid"])
def bilinear_sampler(data, grid, cudnn_off=False):
    """Reference: src/operator/bilinear_sampler.cc — grid (N,2,Ho,Wo) in [-1,1]."""
    n, c, h, w = data.shape
    x = (grid[:, 0] + 1) * (w - 1) / 2.0
    y = (grid[:, 1] + 1) * (h - 1) / 2.0
    return _bilinear_gather(data, x, y)


@register("SpatialTransformer", arg_names=["data", "loc"])
def spatial_transformer(data, loc, target_shape=(0, 0), transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False):
    """Affine spatial transformer: grid generation + bilinear sampling
    (reference: src/operator/spatial_transformer.cc)."""
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


@register("ROIPooling", arg_names=["data", "rois"])
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Reference: src/operator/roi_pooling.cc.  rois (R,5) = (batch, x1,y1,x2,y2).
    Max-pools each quantized bin; bins sampled on a dense sub-grid (4x4 per
    bin) — TPU-friendly gather formulation instead of the reference's per-bin
    scalar loops."""
    ph, pw = pooled_size
    nsamp = 4
    bidx = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rois[:, 1] * spatial_scale)
    y1 = jnp.round(rois[:, 2] * spatial_scale)
    x2 = jnp.round(rois[:, 3] * spatial_scale)
    y2 = jnp.round(rois[:, 4] * spatial_scale)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)

    def one_roi(b, xx1, yy1, wdt, hgt):
        iy = yy1 + (jnp.arange(ph * nsamp) + 0.5) * hgt / (ph * nsamp)
        ix = xx1 + (jnp.arange(pw * nsamp) + 0.5) * wdt / (pw * nsamp)
        yi = jnp.clip(jnp.floor(iy), 0, data.shape[2] - 1).astype(jnp.int32)
        xi = jnp.clip(jnp.floor(ix), 0, data.shape[3] - 1).astype(jnp.int32)
        patch = data[b][:, yi][:, :, xi]  # (C, ph*ns, pw*ns)
        c = patch.shape[0]
        patch = patch.reshape(c, ph, nsamp, pw, nsamp)
        return jnp.max(patch, axis=(2, 4))

    return jax.vmap(one_roi)(bidx, x1, y1, rw, rh)


@register("SVMOutput", arg_names=["data", "label"])
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Reference: src/operator/svm_output.cc — forward is identity over scores."""
    return _svm_core(data, label, margin, regularization_coefficient, use_linear)


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_core(data, label, margin, reg, use_linear):
    return data


def _svm_fwd(data, label, margin, reg, use_linear):
    return data, (data, label)


def _svm_bwd(margin, reg, use_linear, res, g):
    data, label = res
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, data.shape[-1], dtype=data.dtype)
    score_y = jnp.take_along_axis(data, lab[:, None], axis=-1)
    viol = (data - score_y + margin > 0).astype(data.dtype) * (1 - onehot)
    if use_linear:
        grad = viol - onehot * jnp.sum(viol, axis=-1, keepdims=True)
    else:
        m = data - score_y + margin
        grad = 2 * jnp.maximum(m, 0) * (1 - onehot)
        grad = grad - onehot * jnp.sum(grad, axis=-1, keepdims=True)
    return grad * reg, jnp.zeros_like(label)


_svm_core.defvjp(_svm_fwd, _svm_bwd)


@register("Correlation", arg_names=["data1", "data2"], num_outputs=2)
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Reference: src/operator/correlation.cc (FlowNet correlation layer)."""
    n, c, h, w = data1.shape
    d = int(max_displacement)
    p = int(pad_size)
    a = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    b = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    outs = []
    rng = range(-d, d + 1, int(stride2))
    for dy in rng:
        for dx in rng:
            shifted = jnp.roll(b, (-dy, -dx), axis=(2, 3))
            if is_multiply:
                corr = jnp.mean(a * shifted, axis=1)
            else:
                corr = jnp.mean(jnp.abs(a - shifted), axis=1)
            outs.append(corr)
    out = jnp.stack(outs, axis=1)[:, :, p:p + h, p:p + w]
    return out, jnp.zeros_like(data1)
