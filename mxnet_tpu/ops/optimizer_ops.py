"""Fused optimizer-update operators.

Reference: ``src/operator/optimizer_op.cc`` registers the update rules as
first-class ops (sgd_update, sgd_mom_update, mp_sgd_update/mp_sgd_mom_update
with fp32 master weights, adam_update, rmsprop_update/rmspropalex_update,
ftrl_update, ftml_update, signsgd_update/signum_update,
_sparse_adagrad_update), each declaring FMutateInputs for its state tensors
(``optimizer_op-inl.h`` SGDMomKernel et al.).  Here every rule is one pure
jax function returning ``(new_weight, *new_states)``; the registry's
``mutates`` map writes the states back in place, and under a jitted training
step XLA fuses the whole update into the backward program — the fusion the
reference gets from hand-written kernels falls out of the compiler.

Multi-precision (mp_*) variants keep the fp32 master weight as an explicit
input, matching the reference's (weight, grad, [states...], weight32)
signatures, so fp16/bf16 training drives the same op the kvstore server and
user scripts would call.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _clip(g, c):
    """MXNet clip_gradient convention: negative (or None) disables."""
    if c is not None and c >= 0:
        return jnp.clip(g, -c, c)
    return g


def _f32(x):
    return x.astype(jnp.float32)


# ---------------------------------------------------------------------------
# SGD family (reference: optimizer_op-inl.h SGDKernel / SGDMomKernel)
# ---------------------------------------------------------------------------
@register("sgd_update", arg_names=["weight", "grad"], differentiable=False)
def sgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """w = (1 - lr*wd)*w - lr*clip(rescale_grad*g)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    return (1.0 - lr * wd) * weight - lr * g


@register("sgd_mom_update", arg_names=["weight", "grad", "mom"],
          differentiable=False, mutates={2: 1})
def sgd_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """mom = momentum*mom - lr*wd*w - lr*clip(rescale_grad*g); w += mom."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_mom = momentum * mom - lr * wd * weight - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", arg_names=["weight", "grad", "weight32"],
          differentiable=False, mutates={2: 1})
def mp_sgd_update(weight, grad, weight32, lr=None, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """SGD on the fp32 master copy, low-precision weight refreshed from it
    (reference: MP_SGDKernel)."""
    g = _clip(rescale_grad * _f32(grad), clip_gradient)
    w32 = (1.0 - lr * wd) * weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update",
          arg_names=["weight", "grad", "mom", "weight32"],
          differentiable=False, mutates={2: 1, 3: 2})
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=None, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    """Momentum SGD on the fp32 master copy (reference: MP_SGDMomKernel)."""
    g = _clip(rescale_grad * _f32(grad), clip_gradient)
    new_mom = momentum * mom - lr * wd * weight32 - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


# ---------------------------------------------------------------------------
# Sign-based (reference: SignSGDKernel / SignumKernel)
# ---------------------------------------------------------------------------
@register("signsgd_update", arg_names=["weight", "grad"],
          differentiable=False)
def signsgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """w = (1 - lr*wd)*w - lr*sign(g); clip has no effect on a sign."""
    return (1.0 - lr * wd) * weight - lr * jnp.sign(grad)


@register("signum_update", arg_names=["weight", "grad", "mom"],
          differentiable=False, mutates={2: 1})
def signum_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """mom = momentum*mom - (1-momentum)*(wd*w + clip(rescale*g));
    w = (1 - lr*wd_lh)*w + lr*sign(mom)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_mom = momentum * mom - (1.0 - momentum) * wd * weight \
        - (1.0 - momentum) * g
    return (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_mom), new_mom


# ---------------------------------------------------------------------------
# Adam (reference: adam_update — bias correction is applied by the Python
# optimizer through lr, exactly as the reference's optimizer.py does)
# ---------------------------------------------------------------------------
@register("adam_update", arg_names=["weight", "grad", "mean", "var"],
          differentiable=False, mutates={2: 1, 3: 2})
def adam_update(weight, grad, mean, var, lr=None, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """Adam step: update m/v moments and apply the bias-corrected step,
    mutating weight in place (reference: src/operator/optimizer_op.cc
    adam_update)."""
    g = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    out = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return out, new_mean, new_var


# ---------------------------------------------------------------------------
# RMSProp (reference: rmsprop_update = Hinton's slides; rmspropalex_update =
# Graves 2013 with gamma2 momentum and centered variance)
# ---------------------------------------------------------------------------
def _clip_weights(w, cw):
    if cw is not None and cw >= 0:
        return jnp.clip(w, -cw, cw)
    return w


@register("rmsprop_update", arg_names=["weight", "grad", "n"],
          differentiable=False, mutates={2: 1})
def rmsprop_update(weight, grad, n, lr=None, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    """RMSProp step over the squared-gradient running average, in place
    (reference: src/operator/optimizer_op.cc rmsprop_update)."""
    g = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    new_n = (1.0 - gamma1) * jnp.square(g) + gamma1 * n
    out = weight - lr * g / jnp.sqrt(new_n + epsilon)
    return _clip_weights(out, clip_weights), new_n


@register("rmspropalex_update",
          arg_names=["weight", "grad", "n", "g", "delta"],
          differentiable=False, mutates={2: 1, 3: 2, 4: 3})
def rmspropalex_update(weight, grad, n, g, delta, lr=None, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """RMSProp (Graves' variant) step with n/g/delta state, in place
    (reference: src/operator/optimizer_op.cc rmspropalex_update)."""
    gr = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    new_n = (1.0 - gamma1) * jnp.square(gr) + gamma1 * n
    new_g = (1.0 - gamma1) * gr + gamma1 * g
    new_delta = gamma2 * delta \
        - lr * gr / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    out = weight + new_delta
    return _clip_weights(out, clip_weights), new_n, new_g, new_delta


# ---------------------------------------------------------------------------
# Ftrl (reference: FtrlUpdate)
# ---------------------------------------------------------------------------
@register("ftrl_update", arg_names=["weight", "grad", "z", "n"],
          differentiable=False, mutates={2: 1, 3: 2})
def ftrl_update(weight, grad, z, n, lr=None, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """FTRL optimizer step with z/n state, mutating weight in place
    (reference: src/operator/optimizer_op.cc ftrl_update)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_z = z + g - (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) * weight / lr
    new_n = n + jnp.square(g)
    out = (jnp.sign(new_z) * lamda1 - new_z) \
        / ((beta + jnp.sqrt(new_n)) / lr + wd) \
        * (jnp.abs(new_z) > lamda1)
    return out, new_z, new_n


# ---------------------------------------------------------------------------
# FTML (reference: FTMLKernel; note the reference spells the clip param
# ``clip_grad`` for this one op)
# ---------------------------------------------------------------------------
@register("ftml_update", arg_names=["weight", "grad", "d", "v", "z"],
          differentiable=False, mutates={2: 1, 3: 2, 4: 3})
def ftml_update(weight, grad, d, v, z, lr=None, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    """FTML optimizer step mutating weight in place (reference:
    src/operator/optimizer_op.cc ftml_update)."""
    g = _clip(rescale_grad * grad + wd * weight, clip_grad)
    new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
    d_t = (1.0 - beta1 ** t) / lr \
        * (jnp.sqrt(new_v / (1.0 - beta2 ** t)) + epsilon)
    new_z = beta1 * z + (1.0 - beta1) * g - (d_t - beta1 * d) * weight
    return -new_z / d_t, d_t, new_v, new_z


# ---------------------------------------------------------------------------
# Sparse AdaGrad (reference: _sparse_adagrad_update — row-wise history
# update for row_sparse gradients; the dense fallback applies to all rows)
# ---------------------------------------------------------------------------
@register("_sparse_adagrad_update",
          arg_names=["weight", "grad", "history"], differentiable=False,
          mutates={2: 1}, aliases=("sparse_adagrad_update",))
def sparse_adagrad_update(weight, grad, history, lr=None, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Dense-tensor form; RowSparseNDArray gradients take the row-wise path
    in ``optimizer.AdaGrad`` (only touched rows read/written)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    if wd:
        g = g + wd * weight
    new_hist = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_hist) + epsilon), new_hist
