"""Operator registry — the TPU-native analogue of the nnvm op registry.

Reference: ops are registered via NNVM_REGISTER_OP with attributes
(FCompute, FGradient, FInferShape/Type/StorageType, FMutateInputs —
``include/mxnet/op_attr_types.h``).  Here an op is a *pure jax function*
``fn(*arrays, **params) -> array | tuple``; gradients come from ``jax.vjp``
(replacing hand-written FGradient), shape/dtype inference from
``jax.eval_shape`` (replacing the fixpoint passes in
``src/executor/infer_graph_attr_pass.cc``), and XLA replaces FCompute
scheduling.  Metadata kept per-op:

- ``arg_names``: ordered tensor-input names (for Symbol binding / list_arguments)
- ``aux``: mapping input-index -> aux-state name (BatchNorm moving stats);
  aux inputs are excluded from gradients and mutated in place under training
  (reference: FMutateInputs, op_attr_types.h)
- ``aux_update``: fn(inputs, outputs, params) -> {input_idx: new_value}
- ``num_outputs``: int or callable(params)->int
- ``differentiable``: False for integer/ordering ops
"""
from __future__ import annotations

import ast
import functools
import inspect

__all__ = ["Op", "register", "get", "list_ops", "alias"]

_OPS: dict[str, "Op"] = {}

# registration names that overwrote a *different* already-registered op:
# [(name, old_op_name, new_op_name)] — consumed by mxnet_tpu.analysis
# (the nnvm registry aborts on double registration; we record and lint)
_SHADOWS: list[tuple[str, str, str]] = []


def _introspect_fn_params(fn):
    """Positional parameter names of ``fn`` → (names, ok).

    Unwraps ``functools.partial`` chains (dropping already-bound
    positionals and keyword-bound names) and ``__wrapped__`` decorator
    chains before giving up, so partial-registered ops still map scalar
    positional call args onto the right kwargs.  ``ok`` is False only
    when no signature could be recovered at all; the caller falls back
    to ``arg_names`` and mxnet_tpu.analysis reports the fallback.
    """
    drop, bound_kw = 0, set()
    base = fn
    while isinstance(base, functools.partial):
        drop += len(base.args)
        bound_kw |= set(base.keywords or ())
        base = base.func
    for candidate in (fn, base, getattr(base, "__wrapped__", None),
                      getattr(base, "__call__", None)):
        if candidate is None:
            continue
        try:
            sig = inspect.signature(candidate)
        except (TypeError, ValueError):
            continue
        names = [p.name for p in sig.parameters.values()
                 if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)]
        if candidate is not fn:
            # signature came from under the partial: drop bound params
            names = [n for n in names[drop:] if n not in bound_kw]
        return names, True
    return None, False


class Op:
    __slots__ = (
        "name", "fn", "arg_names", "aux", "aux_update", "num_outputs",
        "differentiable", "scalar_args", "doc", "needs_train",
        "optional_args", "fn_params", "fn_params_fallback", "mutates",
    )

    def __init__(self, name, fn, arg_names=None, aux=None, aux_update=None,
                 num_outputs=1, differentiable=True, scalar_args=(),
                 needs_train=False, optional_args=(), mutates=None,
                 doc=None):
        self.name = name
        self.fn = fn
        self.arg_names = list(arg_names) if arg_names else ["data"]
        self.aux = dict(aux) if aux else {}
        self.aux_update = aux_update
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.scalar_args = tuple(scalar_args)
        self.needs_train = needs_train
        # arg names that are NOT auto-created as variables by the symbolic
        # frontend when absent: a tuple of names, or callable(params)->names
        self.optional_args = optional_args
        # unconditional in-place input mutation (reference: FMutateInputs on
        # the optimizer-update ops): {input_idx: fn_output_idx}; the mapped
        # fn outputs are written back into the inputs and only the first
        # num_outputs outputs are public
        self.mutates = dict(mutates) if mutates else {}
        # positional parameter names of fn, so scalar positional call
        # args (nd.swapaxes(x, 0, 1)) map onto the right kwargs
        params, ok = _introspect_fn_params(fn)
        self.fn_params = params if ok else list(self.arg_names)
        self.fn_params_fallback = not ok
        # doc= overrides for generated families (lambdas, partials) whose
        # fn docstring is absent or shared
        self.doc = doc or fn.__doc__ or ""

    def optional(self, params):
        if callable(self.optional_args):
            return set(self.optional_args(params))
        return set(self.optional_args)

    def n_outputs(self, params):
        if callable(self.num_outputs):
            return self.num_outputs(params)
        return self.num_outputs

    def __repr__(self):
        return "Op(%s)" % self.name


def register(name, *, arg_names=None, aux=None, aux_update=None, num_outputs=1,
             differentiable=True, scalar_args=(), aliases=(), needs_train=False,
             optional_args=(), mutates=None, doc=None):
    """Decorator registering a pure jax function as an operator."""

    def deco(fn):
        op = Op(name, fn, arg_names, aux, aux_update, num_outputs,
                differentiable, scalar_args, needs_train, optional_args,
                mutates, doc)
        _register_name(name, op)
        for a in aliases:
            _register_name(a, op)
        return fn

    return deco


def _register_name(name, op):
    old = _OPS.get(name)
    if old is not None and old is not op:
        _SHADOWS.append((name, old.name, op.name))
    _OPS[name] = op


def alias(name, *extra):
    op = _OPS[name]
    for a in extra:
        _register_name(a, op)


def shadowed():
    """Alias/registration collisions recorded so far (for the linter)."""
    return list(_SHADOWS)


def get(name) -> Op:
    try:
        return _OPS[name]
    except KeyError:
        raise KeyError("operator %r is not registered (have %d ops)" % (name, len(_OPS)))


def exists(name) -> bool:
    return name in _OPS


def list_ops():
    return sorted(_OPS)


# ---------------------------------------------------------------------------
# kwarg canonicalization.  The reference crosses the C ABI with string kwargs
# ("(2, 2)", "True"); accept those transparently for script parity.
# ---------------------------------------------------------------------------
_BOOL = {"true": True, "false": False, "True": True, "False": False}


def canonicalize(value):
    if isinstance(value, str):
        if value in _BOOL:
            return _BOOL[value]
        try:
            return ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return value
    return value


def canonicalize_kwargs(kwargs):
    return {k: canonicalize(v) for k, v in kwargs.items()}
