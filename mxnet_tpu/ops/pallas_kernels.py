"""Pallas TPU kernels for the hot ops XLA doesn't fuse optimally.

Reference equivalence: these replace the reference's hand-written CUDA /
cuDNN kernels (SURVEY.md §2.1 "cuDNN integration") for the memory-bound
attention path.  Flash attention streams K/V blocks through VMEM with an
online softmax so the (T×T) score matrix never materializes in HBM —
the standard TPU flash pattern (see /opt/skills/guides/pallas_guide.md).

On non-TPU backends the same kernel runs in Pallas interpret mode, so
tests exercise the real kernel logic on the CPU mesh.

Training: the forward is the Pallas kernel; the backward rematerializes
attention with the jnp formulation under XLA (sound, and XLA's own fusion
handles the backward well; a Pallas backward kernel is a later
optimization).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register

_NEG_INF = -1e30


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _attention_reference(q, k, v, causal, scale):
    """jnp reference: q/k/v (BH, T, D)."""
    s = jnp.einsum("btd,bsd->bts", q, k) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(s.shape[-1])[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bts,bsd->btd", p, v)


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
               causal, scale, block_q, block_k, num_k_blocks, t_k):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _step():
        q = q_ref[0]                                   # (Bq, D)
        k = k_ref[0]                                   # (Bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        kpos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # mask the ragged tail of the last K block (grid padding)
        valid = kpos < t_k
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (qpos >= kpos)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_scr[:, :1]                          # (Bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(jnp.where(m_prev <= _NEG_INF / 2, _NEG_INF, m_prev)
                       - m_safe)
        corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(p, axis=-1,
                                                     keepdims=True)
        # zero padded V rows: p is 0 there, but 0 × garbage/NaN = NaN
        vrow_ok = (ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < t_k
        v_blk = jnp.where(vrow_ok, v_ref[0], 0.0)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:, :1] = m_new

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _flash_attention_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                              interpret):
    """q/k/v: (BH, T, D) → (BH, T, D)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    Tk = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    nq = pl.cdiv(T, block_q)
    nk = pl.cdiv(Tk, block_k)

    kernel = functools.partial(
        _fa_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, num_k_blocks=nk, t_k=Tk)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal, scale):
    interpret = not _on_tpu()
    return _flash_attention_fwd_impl(q, k, v, causal, scale,
                                     block_q=128, block_k=128,
                                     interpret=interpret)


def _flash_fwd(q, k, v, causal, scale):
    return _flash_core(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, g):
    q, k, v = res
    # rematerialized XLA backward (jax.checkpoint-style trade)
    _, vjp = jax.vjp(lambda a, b, c: _attention_reference(a, b, c, causal,
                                                          scale), q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


@register("_contrib_flash_attention", arg_names=["query", "key", "value"],
          aliases=("flash_attention",))
def flash_attention(query, key, value, causal=False, scale=None):
    """Flash attention over (B, T, H, D) tensors (Pallas TPU kernel).

    Memory O(T) instead of O(T²); the per-(batch, head) score blocks live
    only in VMEM.  Works on any backend (interpret mode off-TPU)."""
    B, T, H, D = query.shape
    Tk = key.shape[1]
    if scale is None:
        scale = D ** -0.5

    def to_bh(x, t):
        return x.transpose(0, 2, 1, 3).reshape(B * H, t, x.shape[-1])

    out = _flash_core(to_bh(query, T), to_bh(key, Tk), to_bh(value, Tk),
                      bool(causal), float(scale))
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
